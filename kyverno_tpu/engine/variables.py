"""Variable `{{...}}` and reference `$(...)` substitution.

Re-implementation of pkg/engine/variables/vars.go + regex/regex.go:

- ``{{ expr }}`` resolves against the JSON context via JMESPath; a
  leading backslash escapes. If the variable is the entire string the
  typed value replaces it; embedded variables stringify (JSON for
  non-strings).
- ``{{ @ }}`` expands to a JMESPath of the current position within the
  rule, prefixed with ``request.object`` (or ``target`` when present),
  skipping the first two path segments and any ``foreach``
  (vars.go:332-344).
- ``$(./../x)`` references resolve against the document itself,
  relative to the reference's position; a resolved operator prefix is
  re-attached (vars.go:245-300, 420-460).
- DELETE requests rewrite ``request.object`` to ``request.oldObject``
  (vars.go:346-348).
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, List, Optional, Tuple

from .context import Context, InvalidVariableError
from .operator import Operator, get_operator_from_string_pattern

# regex/regex.go ports
REGEX_VARIABLES = re.compile(r"(^|[^\\])(\{\{(?:\{[^{}]*\}|[^{}])*\}\})")
REGEX_ESCP_VARIABLES = re.compile(r"\\\{\{(?:\{[^{}]*\}|[^{}])*\}\}")
REGEX_REFERENCES = re.compile(r"^\$\(.[^\ ]*\)|[^\\]\$\(.[^\ ]*\)")
REGEX_ESCP_REFERENCES = re.compile(r"\\\$\(.[^\ \)]*\)")
REGEX_VARIABLE_INIT = re.compile(r"^\{\{(?:\{[^{}]*\}|[^{}])*\}\}")


class SubstitutionError(Exception):
    pass


class NotResolvedReferenceError(SubstitutionError):
    pass


def is_variable(value: str) -> bool:
    return isinstance(value, str) and REGEX_VARIABLES.search(value) is not None


def is_reference(value: str) -> bool:
    return isinstance(value, str) and REGEX_REFERENCES.search(value) is not None


VariableResolver = Callable[[Optional[Context], str], Any]


def default_resolver(ctx: Optional[Context], variable: str) -> Any:
    if ctx is None:
        raise InvalidVariableError(f"no context to resolve {variable!r}")
    return ctx.query(variable)


def precondition_resolver(ctx: Optional[Context], variable: str) -> Any:
    """Preconditions resolver (vars.go:42 newPreconditionsVariableResolver).
    Despite its stale upstream comment, it PROPAGATES evaluation errors
    (vars.go:45-53 logs and returns err; vars.go:351-359 surfaces it).
    Unset variables already resolve to None naturally — JMESPath
    returns null for missing paths without erroring — so the lenient
    behavior preconditions need comes from query semantics, not from
    swallowing genuine evaluation errors (type errors, bad syntax)."""
    return default_resolver(ctx, variable)


def substitute_all(ctx: Optional[Context], document: Any, resolver: VariableResolver = default_resolver) -> Any:
    """Port of SubstituteAll (vars.go:58): variables first, then
    references (resolved against the substituted document)."""
    substituted = _walk(document, "/", lambda value, path: _substitute_vars_in_string(ctx, value, path, resolver))
    out = _walk(
        substituted,
        "/",
        lambda value, path: _substitute_refs_in_string(substituted, value, path),
    )
    return out


def substitute_all_in_preconditions(ctx: Optional[Context], document: Any) -> Any:
    return substitute_all(ctx, document, precondition_resolver)


def substitute_vars_only(ctx: Optional[Context], document: Any, resolver: VariableResolver = default_resolver) -> Any:
    return _walk(document, "/", lambda value, path: _substitute_vars_in_string(ctx, value, path, resolver))


def _walk(node: Any, path: str, leaf_fn) -> Any:
    """jsonutils OnlyForLeafsAndKeys traversal: strings (leaves and map
    keys) get transformed; structure is rebuilt."""
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            new_k = leaf_fn(k, path) if isinstance(k, str) else k
            if not isinstance(new_k, str):
                new_k = json.dumps(new_k) if not isinstance(new_k, str) else new_k
            out[new_k] = _walk(v, f"{path}{k}/", leaf_fn)
        return out
    if isinstance(node, list):
        return [_walk(v, f"{path}{i}/", leaf_fn) for i, v in enumerate(node)]
    if isinstance(node, str):
        return leaf_fn(node, path)
    return node


def _path_to_jmespath(segments: List[str]) -> str:
    out = ""
    for seg in segments:
        if seg.isdigit():
            out += f"[{seg}]"
        elif re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", seg):
            out = f"{out}.{seg}" if out else seg
        else:
            quoted = '"%s"' % seg.replace('"', '\\"')
            out = f"{out}.{quoted}" if out else quoted
    return out


def _expand_at(variable: str, path: str, ctx: Optional[Context]) -> str:
    # vars.go:332-344: {{@}} -> request.object.<path minus 2 leading
    # segments, skipping past "foreach">
    path_prefix = "request.object"
    if ctx is not None:
        try:
            if ctx.query("target") is not None:
                path_prefix = "target"
        except InvalidVariableError:
            pass
    segments = [s for s in path.split("/") if s]
    if "foreach" in segments:
        segments = segments[segments.index("foreach") + 1:]
    segments = segments[2:]
    val = _path_to_jmespath(path_prefix.split(".") + segments)
    return variable.replace("@", val)


def _substitute_vars_in_string(ctx: Optional[Context], value: str, path: str, resolver: VariableResolver) -> Any:
    while True:
        matches = [(m.start(2), m.group(2)) for m in REGEX_VARIABLES.finditer(value)]
        if not matches:
            break
        original_pattern = value
        for _, var_text in matches:
            variable = var_text[2:-2].strip()
            # only the bare {{@}} expands (vars.go:332 `variable == "@"`);
            # an @ inside an expression (keys(@)) is JMESPath current-node
            if variable == "@":
                variable = _expand_at(variable, path, ctx)
            if ctx is not None and ctx.query_operation() == "DELETE":
                variable = variable.replace("request.object", "request.oldObject")
            try:
                substituted = resolver(ctx, variable)
            except InvalidVariableError as e:
                raise SubstitutionError(f"failed to resolve {variable} at path {path}: {e}")
            if original_pattern == var_text:
                return substituted  # full-string variable keeps its type
            if isinstance(substituted, str):
                replacement = substituted
            else:
                replacement = json.dumps(substituted, separators=(",", ":"))
            value = value.replace(var_text, replacement, 1)
        if value == original_pattern:
            break
    # unescape \{{...}}
    value = REGEX_ESCP_VARIABLES.sub(lambda m: m.group(0)[1:], value)
    return value


def _substitute_refs_in_string(document: Any, value: str, path: str) -> Any:
    # vars.go substituteReferencesIfAny
    while True:
        m = REGEX_REFERENCES.search(value)
        if not m:
            break
        full = m.group(0)
        initial = full.startswith("$(")
        ref = full if initial else full[1:]
        resolved = _resolve_reference(document, ref, path)
        if resolved is None:
            raise NotResolvedReferenceError(f"reference {ref} not resolved at path {path}")
        if isinstance(resolved, str):
            replacement = ("" if initial else full[0]) + resolved
            value = value.replace(full, replacement, 1)
            continue
        raise NotResolvedReferenceError(f"reference {ref} not resolved at path {path}")
    value = REGEX_ESCP_REFERENCES.sub(lambda m: m.group(0)[1:], value)
    return value


def _resolve_reference(document: Any, reference: str, absolute_path: str) -> Optional[str]:
    # vars.go resolveReference:432-460
    path = reference.strip("$()")
    op = get_operator_from_string_pattern(path)
    path = path[len(op.value):]
    if not path:
        return None
    abs_segments = _form_absolute_path(path, absolute_path)
    val = _get_value_by_path(document, abs_segments)
    if val is None:
        return None
    if op is Operator.EQUAL:
        if isinstance(val, str):
            return val
        return _val_to_string(val)
    s = _val_to_string(val)
    if s is None:
        return None
    return op.value + s


def _val_to_string(value: Any) -> Optional[str]:
    if isinstance(value, str):
        return value
    if isinstance(value, bool):
        return None
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        return "%f" % value
    return None


def _form_absolute_path(reference_path: str, absolute_path: str) -> List[str]:
    if reference_path.startswith("/"):
        return [s for s in reference_path.split("/") if s]
    base = [s for s in absolute_path.split("/") if s]
    for seg in reference_path.split("/"):
        if seg == "." or seg == "":
            continue
        elif seg == "..":
            if base:
                base.pop()
        else:
            base.append(seg)
    return base


def _get_value_by_path(document: Any, segments: List[str]) -> Any:
    node = document
    for seg in segments:
        if isinstance(node, dict):
            if seg in node:
                node = node[seg]
            else:
                # anchored keys resolve by their inner key
                from . import anchor as anchorpkg

                found = None
                for k in node:
                    a = anchorpkg.parse(k)
                    if a is not None and a.key == seg:
                        found = node[k]
                        break
                if found is None:
                    return None
                node = found
        elif isinstance(node, list):
            if seg.isdigit() and int(seg) < len(node):
                node = node[int(seg)]
            else:
                return None
        else:
            return None
    return node
