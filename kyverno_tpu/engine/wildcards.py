"""Wildcard expansion in pattern metadata and selectors.

Re-implementation of pkg/engine/wildcards/wildcards.go: validation
patterns may use glob wildcards in `metadata.labels` /
`metadata.annotations` *keys*; before matching, those keys are
expanded against the keys actually present on the resource
(ExpandInMetadata). Label selectors get both keys and values expanded
(ReplaceInSelector), with unmatched wildcard characters replaced by
'0' since Kubernetes selectors reject them.

Unlike the Go code (which mutates the pattern map in place,
wildcards.go:80-86), we return a fresh map so compiled policies stay
immutable across evaluations.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..utils import wildcard
from . import anchor as anchorpkg


def replace_in_selector(match_labels: Dict[str, str], resource_labels: Dict[str, str]) -> Dict[str, str]:
    """Port of ReplaceInSelector (wildcards.go:14) for matchLabels."""
    result: Dict[str, str] = {}
    for k, v in match_labels.items():
        if wildcard.contains_wildcard(k) or wildcard.contains_wildcard(v):
            mk, mv = _expand_wildcards(k, v, resource_labels, match_value=True, replace=True)
            result[mk] = mv
        else:
            result[k] = v
    return result


def _expand_wildcards(
    k: str, v: str, resource_map: Dict[str, str], match_value: bool, replace: bool
) -> Tuple[str, str]:
    for k1, v1 in resource_map.items():
        if wildcard.match(k, k1):
            if not match_value:
                return k1, v1
            elif wildcard.match(v, v1):
                return k1, v1
    if replace:
        k = _replace_wildcard_chars(k)
        v = _replace_wildcard_chars(v)
    return k, v


def _replace_wildcard_chars(s: str) -> str:
    return s.replace("*", "0").replace("?", "0")


def expand_in_metadata(pattern_map: Dict[str, Any], resource_map: Dict[str, Any]) -> Dict[str, Any]:
    """Port of ExpandInMetadata (wildcards.go:62)."""
    meta_key, pattern_metadata = _get_pattern_value("metadata", pattern_map)
    if pattern_metadata is None or not isinstance(pattern_metadata, dict):
        return pattern_map
    resource_metadata = resource_map.get("metadata")
    if resource_metadata is None:
        return pattern_map

    metadata = dict(pattern_metadata)
    labels_key, labels = _expand_wildcards_in_tag("labels", pattern_metadata, resource_metadata)
    if labels is not None:
        metadata[labels_key] = labels
    annotations_key, annotations = _expand_wildcards_in_tag(
        "annotations", pattern_metadata, resource_metadata
    )
    if annotations is not None:
        metadata[annotations_key] = annotations
    result = dict(pattern_map)
    result[meta_key] = metadata
    return result


def _get_pattern_value(tag: str, pattern: Dict[str, Any]) -> Tuple[str, Any]:
    for k, v in pattern.items():
        if k == tag:
            return k, v
        a = anchorpkg.parse(k)
        if a is not None and a.key == tag:
            return k, v
    return "", None


def _expand_wildcards_in_tag(tag: str, pattern_metadata: Any, resource_metadata: Any):
    pattern_key, pattern_data = _get_value_as_string_map(tag, pattern_metadata)
    if pattern_data is None:
        return "", None
    _, resource_data = _get_value_as_string_map(tag, resource_metadata)
    if resource_data is None:
        return "", None
    return pattern_key, _replace_wildcards_in_map_keys(pattern_data, resource_data)


def _get_value_as_string_map(key: str, data: Any) -> Tuple[str, Optional[Dict[str, str]]]:
    if not isinstance(data, dict):
        return "", None
    pattern_key, val = _get_pattern_value(key, data)
    if val is None or not isinstance(val, dict):
        return "", None
    result: Dict[str, str] = {}
    for k, v in val.items():
        if not isinstance(v, str):
            return "", None  # Go would panic on the cast; treat as not-expandable
        result[k] = v
    return pattern_key, result


def _replace_wildcards_in_map_keys(
    pattern_data: Dict[str, str], resource_data: Dict[str, str]
) -> Dict[str, Any]:
    results: Dict[str, Any] = {}
    for k, v in pattern_data.items():
        if wildcard.contains_wildcard(k):
            a = anchorpkg.parse(k)
            if a is not None:
                match_k, _ = _expand_wildcards(a.key, v, resource_data, match_value=False, replace=False)
                results[anchorpkg.anchor_string(a.modifier, match_k)] = v
            else:
                match_k, _ = _expand_wildcards(k, v, resource_data, match_value=False, replace=False)
                results[match_k] = v
        else:
            results[k] = v
    return results
