"""Fleet layer — multi-replica scan sharding, failover, and peered
verdict caches (ROADMAP item 1, the scale-out pillar).

One process is one failure domain. The fleet layer turns N replica
processes (``serve --fleet-listen/--fleet-peers/--replica-id``) into
one logical engine:

- **membership** (membership.py): lease-based liveness extending
  cluster/leaderelection.py — every replica heartbeats its peers over
  localhost HTTP; a replica whose lease expires (crash, hang,
  partition) drops out of the live set within the lease TTL. The
  lowest-id live replica is the leader and stamps the rebalance epoch.
- **shards** (shards.py): the resource keyspace is split into fixed
  shards; rendezvous hashing assigns each shard to exactly one live
  replica, so a membership change moves ONLY the dead replica's
  shards — the rest of the fleet keeps its warm state.
- **peering** (peering.py): verdict-cache fetch-on-miss plus async
  push of freshly computed columns between replicas. Content-addressed
  keys (tpu/cache.py) make peering safe by construction: a
  wrong-revision entry never matches the requested key, and every
  response is checksum- and key-re-verified on receipt — a poisoned
  or truncated peer answer is a MISS, never a wrong verdict.
- **manager** (manager.py): ties the above into one FleetManager the
  scanner, webhooks, and /debug/fleet consume.
- **telemetry** (telemetry.py): the fleet observability plane — every
  replica serves a checksummed telemetry snapshot on
  ``/fleet/telemetry``; the leader pulls on the heartbeat cadence,
  folds snapshots through a trust ladder (checksum -> schema ->
  replay/ordering -> staleness, rejects dropped-and-counted) into the
  monotonic ``kyverno_fleet_agg_*`` families and a fleet-wide SLO
  burn, and gossips the rollup back so any replica answers
  ``/debug/fleet``. Peer RPCs carry the caller's trace context, so a
  peer-served admission is ONE connected trace across replicas.

Degradation ladder: peer fetch -> local compute -> scalar oracle.
Every remote interaction runs under a per-peer circuit breaker and a
deadline budget (fault sites fleet.heartbeat / fleet.peer_fetch /
fleet.gossip / fleet.telemetry), so a dead or partitioned peer costs
one bounded timeout, never a retry storm and never a missing verdict.
"""

from .manager import (FleetConfig, FleetManager, configure_fleet,
                      get_fleet, reset_fleet)
from .shards import rendezvous_owner, shard_of
from .telemetry import (TELEMETRY_SCHEMA_VERSION, TelemetryAggregator,
                        TelemetrySource, snapshot_checksum)

__all__ = [
    "FleetConfig", "FleetManager", "configure_fleet", "get_fleet",
    "reset_fleet", "shard_of", "rendezvous_owner",
    "TELEMETRY_SCHEMA_VERSION", "TelemetryAggregator",
    "TelemetrySource", "snapshot_checksum",
]
