"""FleetManager — membership, shard ownership, and cache peering as
one lifecycle the control plane starts and stops.

The manager runs two daemon threads next to the peer server:

- the **heartbeat loop** renews this replica's own lease, heartbeats
  every known peer (per-peer breaker, ``fleet.heartbeat`` fault
  site), merges discovered peer URLs, and recomputes the rendezvous
  shard map whenever the live set changes — a takeover marks the
  gained shards for forced rescan and seeds their freshness from the
  dead owner's last gossiped stamp, so the scan-freshness SLO tells
  the truth about data that went stale with its owner;
- the **gossip loop** drains the push queue of freshly computed
  verdict columns and fans them to live peers (``fleet.gossip``
  site) so one replica's scan warms the whole fleet.

Everything here degrades, nothing here blocks serving: the scanner
and webhooks consult the manager through lock-free-per-tick snapshot
views, remote calls happen on the fleet threads or inside explicit
deadline budgets, and a fleet with zero live peers behaves exactly
like the single-replica engine.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

from .membership import FleetMembership
from .peering import CacheKey, PeerCacheClient, PushQueue
from .shards import DEFAULT_NUM_SHARDS, owned_shards, shard_of
from .telemetry import (TELEMETRY_SCHEMA_VERSION, TelemetryAggregator,
                        TelemetrySource)


@dataclass(frozen=True)
class FleetConfig:
    replica_id: str
    listen_port: int = 0
    peers: Tuple[str, ...] = ()       # static peer base URLs
    lease_s: float = 3.0
    heartbeat_interval_s: Optional[float] = None   # default lease_s / 4
    num_shards: int = DEFAULT_NUM_SHARDS
    fetch_budget_s: float = 0.15      # admission-path single-key fetch
    scan_fetch_budget_s: float = 1.0  # scan-path batch fetch
    push_interval_s: float = 0.2
    push_max_batch: int = 256
    fetch_max_keys: int = 1024
    # a telemetry snapshot older than this is history, not state — the
    # aggregator's staleness rung drops it (0 disables the age check)
    telemetry_max_age_s: float = 30.0

    @property
    def heartbeat_s(self) -> float:
        hb = self.heartbeat_interval_s
        return hb if hb else max(self.lease_s / 4.0, 0.05)


class FleetManager:
    def __init__(self, config: FleetConfig, cache=None, metrics=None,
                 clock=time.monotonic):
        from .server import FleetPeerServer

        if config.num_shards <= 0:
            # zero shards = every replica owns nothing = the scanner
            # silently skips everything while freshness stays green —
            # a misconfiguration, never a mode
            raise ValueError(
                f"fleet num_shards must be positive, got "
                f"{config.num_shards}")
        self.config = config
        self._clock = clock
        self._metrics = metrics
        if cache is None:
            from ..tpu.cache import global_verdict_cache

            cache = global_verdict_cache
        self.cache = cache
        self.server = FleetPeerServer(self, port=config.listen_port)
        self.url = f"http://127.0.0.1:{self.server.port}"
        self.membership = FleetMembership(
            config.replica_id, url=self.url, lease_s=config.lease_s,
            clock=clock)
        self.client = PeerCacheClient(
            metrics=metrics, fetch_budget_s=config.fetch_budget_s,
            scan_fetch_budget_s=config.scan_fetch_budget_s)
        self._push_q = PushQueue(metrics=metrics)
        # optional provider of the active compiled set's rule count —
        # the push-receive shape check (ControlPlane wires it)
        self.rows_provider: Optional[Callable[[], Optional[int]]] = None
        self._lock = threading.Lock()
        self._owned: FrozenSet[int] = frozenset()       # guarded-by: _lock
        self._pending_takeover: Set[int] = set()        # guarded-by: _lock
        # wall-clock stamp of the last scan tick covering each owned
        # shard (wall, not monotonic: stamps cross process boundaries
        # in heartbeats)
        self._shard_fresh: Dict[int, float] = {}        # guarded-by: _lock
        self._started = False
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._gossip_thread: Optional[threading.Thread] = None
        # peers added after construction (tests wire ephemeral ports;
        # production uses config.peers + heartbeat discovery)
        self._extra_peers: Set[str] = set()             # guarded-by: _lock
        # telemetry plane: every replica SERVES snapshots; only the
        # leader PULLS and folds them, then gossips the rollup back so
        # any replica can answer /debug/fleet with the fleet view
        self.telemetry = TelemetrySource(self)
        self.aggregator = TelemetryAggregator(
            metrics=metrics, clock=clock,
            max_age_s=config.telemetry_max_age_s)
        self._rollup: Optional[Dict[str, Any]] = None   # guarded-by: _lock

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    # -- lifecycle

    @property
    def active(self) -> bool:
        return self._started and not self._stop.is_set()

    def start(self) -> "FleetManager":
        self.server.start()
        self.membership.renew_self()
        self._recompute_shards(reason="initial")
        # local puts of freshly computed columns fan out to peers
        # asynchronously; receive-side stores use cache_store (no
        # re-push, so a column cannot ping-pong across the fleet)
        try:
            self.cache.on_put = self._on_local_put
        except Exception:
            pass
        self._stop.clear()
        self._started = True
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="fleet-heartbeat")
        self._hb_thread.start()
        self._gossip_thread = threading.Thread(
            target=self._gossip_loop, daemon=True, name="fleet-gossip")
        self._gossip_thread.start()
        return self

    def stop(self, leave: bool = True) -> None:
        self._stop.set()
        if getattr(self.cache, "on_put", None) is self._on_local_put:
            self.cache.on_put = None
        if leave and self._started:
            # graceful leave: tell peers now instead of making them
            # wait out the lease TTL (a SIGKILLed replica never gets
            # here — that IS the failover path)
            try:
                self._send_heartbeats(leaving=True)
            except Exception:
                pass
        for t in (self._hb_thread, self._gossip_thread):
            if t is not None:
                t.join(timeout=5)
        self.server.stop()
        self._started = False

    def kill(self) -> None:
        """Test hook: die like SIGKILL — stop renewing and answering
        with NO leave notification, so peers must detect the expired
        lease."""
        self._stop.set()
        if getattr(self.cache, "on_put", None) is self._on_local_put:
            self.cache.on_put = None
        for t in (self._hb_thread, self._gossip_thread):
            if t is not None:
                t.join(timeout=5)
        self.server.stop()

    # -- heartbeat / membership

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                pass  # the heartbeat loop must survive anything
            self._stop.wait(self.config.heartbeat_s)

    def tick(self) -> None:
        """One heartbeat round: renew self, heartbeat peers, absorb
        membership changes. Public so tests can drive time."""
        self.membership.renew_self()
        self._send_heartbeats()
        changed, _epoch, _live = self.membership.note_epoch_if_changed()
        if changed:
            self._recompute_shards(reason="membership")
        self._telemetry_round()
        self._publish_gauges()

    def add_peers(self, *urls: str) -> None:
        """Add peer base URLs after construction (ephemeral-port test
        wiring; equivalent to listing them in config.peers)."""
        with self._lock:
            self._extra_peers.update(u.rstrip("/") for u in urls if u)

    def _heartbeat_targets(self) -> List[Tuple[str, str]]:
        """Static config peers + everything discovered, keyed by URL —
        heartbeats go to configured peers even before we know their
        replica ids (that IS the discovery)."""
        targets: Dict[str, str] = {}
        with self._lock:
            extra = list(self._extra_peers)
        for url in list(self.config.peers) + extra:
            targets[url.rstrip("/")] = ""
        for rid, url in self.membership.peers():
            targets[url.rstrip("/")] = rid
        targets.pop(self.url, None)
        return [(rid, url) for url, rid in targets.items()]

    def _send_heartbeats(self, leaving: bool = False) -> None:
        m = self._registry()
        with self._lock:
            fresh = {str(s): t for s, t in self._shard_fresh.items()
                     if s in self._owned}
        doc = {
            "replica_id": self.config.replica_id,
            "url": self.url,
            "lease_s": self.config.lease_s,
            "epoch": self.membership.epoch,
            "shard_fresh": fresh,
        }
        if leaving:
            doc["leaving"] = True
        if self.membership.is_leader():
            # the leader piggybacks its fleet rollup on every heartbeat
            # it SENDS, so followers hold the fleet view without a
            # second RPC (and serve /debug/fleet themselves)
            rollup = self.rollup_view()
            if rollup is not None:
                doc = dict(doc)
                doc["rollup"] = rollup
        for rid, url in self._heartbeat_targets():
            link = self.client.link(rid or url, url)
            resp = link.call("/fleet/heartbeat", doc,
                             budget_s=max(self.config.heartbeat_s, 0.25),
                             site="fleet.heartbeat",
                             payload=rid or url,
                             # control plane: interval-limited and
                             # budget-bounded, never breaker-gated (a
                             # healthy heartbeat must not whitewash a
                             # broken data plane, and an open breaker
                             # must not fabricate a failover)
                             use_breaker=False)
            if resp is None:
                m.fleet_heartbeats.inc({"peer": rid or url,
                                        "outcome": "error"})
                continue
            m.fleet_heartbeats.inc({"peer": rid or url, "outcome": "ok"})
            # the response is the peer's own heartbeat back at us:
            # renew its lease and learn any members it knows
            peer_id = resp.get("replica_id", "")
            if peer_id:
                self.membership.observe_heartbeat(
                    peer_id, url=url, lease_s=resp.get("lease_s"))
                if (rid or url) != peer_id:
                    # re-key the breaker link under the real id (the
                    # provisional URL-keyed one is dropped)
                    self.client.rekey(rid or url, peer_id, url)
            for other, other_url in (resp.get("members") or {}).items():
                # discovery only — a third-party view never renews
                self.membership.learn_url(other, other_url)
            # the response may carry the leader's rollup back at us
            self._absorb_rollup(resp.get("rollup"))

    def on_heartbeat(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Server side of /fleet/heartbeat."""
        rid = doc.get("replica_id", "")
        if doc.get("leaving"):
            self.membership.forget(rid)
        else:
            self.membership.observe_heartbeat(
                rid, url=doc.get("url", ""), lease_s=doc.get("lease_s"),
                shard_fresh=doc.get("shard_fresh"))
        changed, _epoch, _live = self.membership.note_epoch_if_changed()
        if changed:
            self._recompute_shards(reason="membership")
        self._absorb_rollup(doc.get("rollup"))
        members = self.membership.known_urls()
        resp = {"replica_id": self.config.replica_id,
                "lease_s": self.config.lease_s,
                "epoch": self.membership.epoch,
                "members": members}
        if self.membership.is_leader():
            rollup = self.rollup_view()
            if rollup is not None:
                resp["rollup"] = rollup
        return resp

    # -- telemetry plane

    def _telemetry_round(self) -> None:
        """Leader-only fold on the heartbeat cadence: ingest our own
        snapshot, pull every live peer's ``/fleet/telemetry``, run each
        through the aggregator's trust ladder, then recompute and store
        the rollup (heartbeats gossip it back out). Followers do
        nothing here — they serve snapshots and absorb rollups."""
        if not self.membership.is_leader():
            return
        m = self._registry()
        agg = self.aggregator
        agg.ingest(self.telemetry.build())
        for rid, url in self.membership.peers():
            link = self.client.link(rid, url)
            resp = link.call("/fleet/telemetry", {},
                             budget_s=max(self.config.heartbeat_s, 0.25),
                             site="fleet.telemetry",
                             payload=rid,
                             # control plane, like heartbeats: interval-
                             # limited and budget-bounded, not breaker-
                             # gated
                             use_breaker=False)
            if resp is None:
                m.fleet_telemetry_pulls.inc({"peer": rid,
                                             "outcome": "error"})
                continue
            reason = agg.ingest(resp)
            m.fleet_telemetry_pulls.inc(
                {"peer": rid,
                 "outcome": "rejected" if reason else "ok"})
        live = set(self.membership.live()) | {self.config.replica_id}
        agg.prune(live)
        rollup = agg.rollup(self.config.replica_id, self.membership.epoch)
        with self._lock:
            self._rollup = rollup
        agg.publish_gauges()
        agg.publish_burn(rollup)

    def _absorb_rollup(self, rollup: Any) -> None:
        """Keep the newest rollup we have seen (by its wall stamp); a
        rollup from a different telemetry schema is ignored, never
        half-trusted."""
        if not isinstance(rollup, dict):
            return
        if rollup.get("schema_version") != TELEMETRY_SCHEMA_VERSION:
            return
        try:
            at = float(rollup.get("at", 0.0))
        except (TypeError, ValueError):
            return
        with self._lock:
            cur = self._rollup
            if cur is None or float(cur.get("at", 0.0)) <= at:
                self._rollup = rollup

    def rollup_view(self) -> Optional[Dict[str, Any]]:
        """The newest fleet rollup this replica holds — computed here
        if we lead, gossiped to us otherwise (None before the first
        fold reaches us)."""
        with self._lock:
            return self._rollup

    def slo_advisory(self) -> Dict[str, Any]:
        """The advisory fleet block /readyz attaches under its slo
        detail: fleet-aggregated divergence flips the degraded bit —
        advisory like the rest of the slo block, never a hard fail."""
        rollup = self.rollup_view()
        if rollup is None:
            return {"rollup": False, "degraded": False}
        return {
            "rollup": True,
            "degraded": bool(rollup.get("degraded")),
            "computed_by": rollup.get("computed_by"),
            "rollup_age_s": round(
                max(0.0, time.time() - float(rollup.get("at", 0.0))), 3),
            "divergence_total": (rollup.get("totals") or {}).get(
                "verification_divergences", 0.0),
            "burn": rollup.get("burn") or {},
        }

    # -- shard ownership

    def _recompute_shards(self, reason: str) -> None:
        live = self.membership.live() or [self.config.replica_id]
        mine = frozenset(owned_shards(self.config.replica_id, live,
                                      self.config.num_shards))
        now_wall = time.time()
        m = self._registry()
        with self._lock:
            gained = mine - self._owned
            self._owned = mine
            # a shard lost again before its takeover rescan ran is the
            # new owner's problem now — keep pending truthful
            self._pending_takeover &= set(mine)
            for shard in gained:
                self._pending_takeover.add(shard)
                if shard not in self._shard_fresh:
                    seed = self.membership.gossiped_freshness(shard)
                    if seed is None:
                        # no prior owner report: fresh at birth for the
                        # initial assignment, one lease TTL stale for a
                        # takeover (the data is at LEAST that old)
                        seed = (now_wall if reason == "initial"
                                else now_wall - self.config.lease_s)
                    self._shard_fresh[shard] = seed
            # shards we lost stop feeding our freshness view
            for shard in list(self._shard_fresh):
                if shard not in mine:
                    del self._shard_fresh[shard]
        if gained:
            m.fleet_shard_reassignments.inc(
                {"reason": reason}, value=len(gained))
            try:
                from ..observability.log import global_oplog

                global_oplog.emit(
                    "fleet_shards_reassigned", reason=reason,
                    gained=len(gained), owned=len(mine),
                    epoch=self.membership.epoch, live=live)
            except Exception:
                pass
        self._publish_gauges()

    def owned_view(self) -> FrozenSet[int]:
        """One consistent ownership snapshot per scan tick."""
        with self._lock:
            return self._owned

    def owns(self, uid: str) -> bool:
        return shard_of(uid, self.config.num_shards) in self.owned_view()

    def take_newly_owned(self) -> FrozenSet[int]:
        """Shards gained since the last call — the scanner force-
        rescans their resources (the dead owner's reports died with
        it; clean-skip bookkeeping must not hide that)."""
        with self._lock:
            pending = frozenset(self._pending_takeover)
            self._pending_takeover.clear()
        return pending

    def pending_takeover(self) -> FrozenSet[int]:
        """Non-destructive view of the takeover set: the scanner peeks
        at tick START and clears at tick COMPLETION (note_scan_tick),
        so a tick that dies mid-scan retries the takeover instead of
        silently losing it."""
        with self._lock:
            return frozenset(self._pending_takeover)

    def note_scan_tick(self, covered: FrozenSet[int],
                       taken: Optional[FrozenSet[int]] = None) -> float:
        """A scan tick covering ``covered`` completed: stamp them
        fresh and return the fleet-aware freshness LAG — seconds by
        which the OLDEST owned shard trails now (0 when everything
        owned was just covered). The scan service feeds this into the
        scan-freshness SLO so a takeover shows as staleness until the
        takeover rescan lands."""
        now_wall = time.time()
        with self._lock:
            for shard in covered:
                if shard in self._owned:
                    self._shard_fresh[shard] = now_wall
            if taken:
                # this completed tick honored the takeover rescan —
                # but ONLY for shards the tick actually covered: a
                # shard gained between the scanner's owned_view() and
                # pending_takeover() reads was skipped as unowned this
                # tick and must stay pending for the next one
                self._pending_takeover -= (set(taken) & set(covered))
            stamps = [self._shard_fresh.get(s, now_wall - self.config.lease_s)
                      for s in self._owned]
        lag = max(0.0, now_wall - min(stamps)) if stamps else 0.0
        self._registry().fleet_shard_staleness.set(round(lag, 3))
        return lag

    # -- cache peering

    def _on_local_put(self, key: CacheKey, column: np.ndarray) -> None:
        self._push_q.offer(key, column)

    def cache_peek(self, key: CacheKey) -> Optional[np.ndarray]:
        """Local-only lookup for the peer-fetch server path (peers
        probing us must not skew our own hit-rate accounting)."""
        peek = getattr(self.cache, "peek", None)
        return peek(key) if peek is not None else None

    def cache_store(self, key: CacheKey, column: np.ndarray) -> None:
        """Store a verified peer column WITHOUT re-fanout."""
        self.cache.put(key, column, fanout=False)

    def expected_rows(self) -> Optional[int]:
        if self.rows_provider is None:
            return None
        try:
            return self.rows_provider()
        except Exception:
            return None

    def fetch_missing(self, keys, expect_rows: int
                      ) -> Dict[CacheKey, np.ndarray]:
        """Scan-path batch fetch from live peers; verified hits land
        in the local cache (no re-fanout) and are returned."""
        peers = self.membership.peers()
        if not peers or not keys:
            return {}
        got = self.client.fetch(peers, keys, expect_rows)
        for key, col in got.items():
            self.cache_store(key, col)
        return got

    def fetch_one(self, key, expect_rows: int) -> Optional[np.ndarray]:
        """Admission-path single-key fetch under the tight budget."""
        peers = self.membership.peers()
        if not peers:
            return None
        col = self.client.fetch_one(peers, key, expect_rows)
        if col is not None:
            self.cache_store(tuple(key), col)
        return col

    # -- gossip

    def _gossip_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.gossip_once()
            except Exception:
                pass
            self._stop.wait(self.config.push_interval_s)

    def gossip_once(self) -> int:
        """Drain one push batch to live peers; returns entries sent.
        With no live peer the queue is left intact (the bounded deque
        drops-oldest under pressure) — columns computed before the
        first heartbeat exchange still warm peers that join late."""
        peers = self.membership.peers()
        if not peers:
            return 0
        entries = self._push_q.drain(self.config.push_max_batch)
        if not entries:
            return 0
        self.client.push(peers, entries)
        return len(entries)

    # -- introspection

    def _publish_gauges(self) -> None:
        m = self._registry()
        live = self.membership.live()
        m.fleet_replicas.set(len(live))
        m.fleet_is_leader.set(1 if self.membership.is_leader() else 0)
        m.fleet_epoch.set(self.membership.epoch)
        with self._lock:
            m.fleet_shards_owned.set(len(self._owned))

    def state(self) -> Dict[str, Any]:
        with self._lock:
            owned = sorted(self._owned)
            pending = sorted(self._pending_takeover)
            now_wall = time.time()
            fresh = {str(s): round(now_wall - t, 3)
                     for s, t in sorted(self._shard_fresh.items())}
            rollup = self._rollup
        return {
            "enabled": True,
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "membership": self.membership.state(),
            "shards": {
                "num_shards": self.config.num_shards,
                "owned": owned,
                "owned_count": len(owned),
                "pending_takeover": pending,
                "staleness_s": fresh,
            },
            "peering": {
                "breakers": self.client.breaker_states(),
                "push_queue_depth": len(self._push_q),
                "fetch_budget_s": self.config.fetch_budget_s,
                "scan_fetch_budget_s": self.config.scan_fetch_budget_s,
            },
            "telemetry": {
                "boot_id": self.telemetry.boot_id,
                "seq": self.telemetry.seq,
                "is_leader": self.membership.is_leader(),
                "max_age_s": self.config.telemetry_max_age_s,
                "rollup_age_s": (round(max(
                    0.0, time.time() - float(rollup.get("at", 0.0))), 3)
                    if rollup else None),
                "rollup": rollup,
            },
        }


# ---------------------------------------------------------------------------
# process-global fleet (like the caches: one replica per process)

_fleet_lock = threading.Lock()
_global_fleet: Optional[FleetManager] = None


def configure_fleet(config: Optional[FleetConfig] = None,
                    **kw) -> Optional[FleetManager]:
    """Install (and start) the process-wide FleetManager; None/empty
    config tears it down. Keyword form builds the config in place."""
    global _global_fleet
    if config is None and kw:
        config = FleetConfig(**kw)
    with _fleet_lock:
        old, _global_fleet = _global_fleet, None
    if old is not None:
        try:
            old.stop()
        except Exception:
            pass
    if config is None:
        return None
    mgr = FleetManager(config).start()
    with _fleet_lock:
        _global_fleet = mgr
    return mgr


def get_fleet() -> Optional[FleetManager]:
    with _fleet_lock:
        return _global_fleet


def reset_fleet() -> None:
    configure_fleet(None)


def current_replica_id() -> Optional[str]:
    """The replica id flight records and op-log events are tagged
    with (None outside a fleet)."""
    mgr = get_fleet()
    return mgr.config.replica_id if mgr is not None else None
