"""Lease-based fleet membership — liveness as a lease ledger.

Extends cluster/leaderelection.py: the same ``LeaseStore`` TTL
semantics that elect singleton controllers also decide which replicas
are alive. Every replica renews its OWN lease locally on each
heartbeat tick and renews a PEER's lease whenever that peer's
heartbeat arrives over the peer protocol; a replica that stops
heartbeating (SIGKILL, hang, partition) simply stops renewing and
falls out of ``live()`` when its lease duration elapses — crash
detection without a failure detector beyond the lease clock.

Leadership is derived, not elected: the lexicographically smallest
live replica id is the leader (every replica computes the same answer
from its own ledger), and the leader stamps the rebalance epoch the
shard map is versioned by. A dead leader loses its lease like any
other replica and leadership moves with no extra protocol.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..cluster.leaderelection import LeaseStore

_LEASE_PREFIX = "fleet/replica/"


class FleetMembership:
    """One replica's view of the fleet, backed by a LeaseStore."""

    def __init__(self, replica_id: str, url: str = "",
                 lease_s: float = 3.0, store: Optional[LeaseStore] = None,
                 clock=time.monotonic):
        self.replica_id = replica_id
        self.url = url
        self.lease_s = float(lease_s)
        self._clock = clock
        self.store = store if store is not None else LeaseStore(clock=clock)
        self._lock = threading.Lock()
        self._urls: Dict[str, str] = {replica_id: url}  # guarded-by: _lock
        self._epoch = 0                                 # guarded-by: _lock
        self._live_view: Tuple[str, ...] = ()           # guarded-by: _lock
        # per-replica wall-clock freshness stamps for the shards each
        # peer reported owning+scanning (heartbeat payload); survivors
        # seed takeover freshness from the dead owner's last report
        self._shard_fresh: Dict[int, float] = {}        # guarded-by: _lock

    # -- lease plumbing

    def renew_self(self) -> None:
        self.store.try_acquire_or_renew(
            _LEASE_PREFIX + self.replica_id, self.replica_id, self.lease_s)

    def observe_heartbeat(self, replica_id: str, url: str = "",
                          lease_s: Optional[float] = None,
                          shard_fresh: Optional[Dict[str, float]] = None,
                          ) -> None:
        """A peer's heartbeat arrived: renew its lease in OUR ledger.
        Only direct heartbeats renew — a third party's stale view of a
        dead replica must never keep its lease alive here."""
        if not replica_id or replica_id == self.replica_id:
            return
        self.store.try_acquire_or_renew(
            _LEASE_PREFIX + replica_id, replica_id,
            float(lease_s) if lease_s else self.lease_s)
        with self._lock:
            if url:
                self._urls[replica_id] = url
            if shard_fresh:
                for shard, ts in shard_fresh.items():
                    try:
                        s, t = int(shard), float(ts)
                    except (TypeError, ValueError):
                        continue
                    if t > self._shard_fresh.get(s, 0.0):
                        self._shard_fresh[s] = t

    def forget(self, replica_id: str) -> None:
        """Graceful leave: release the peer's lease immediately instead
        of waiting out the TTL."""
        self.store.release(_LEASE_PREFIX + replica_id, replica_id)

    # -- views

    def live(self) -> List[str]:
        """Replica ids with a fresh lease, self included, sorted —
        the deterministic input every replica feeds rendezvous."""
        with self._lock:
            known = list(self._urls)
        alive = [rid for rid in known
                 if self.store.holder(_LEASE_PREFIX + rid) == rid]
        return sorted(alive)

    def leader(self) -> Optional[str]:
        alive = self.live()
        return alive[0] if alive else None

    def is_leader(self) -> bool:
        return self.leader() == self.replica_id

    def url_of(self, replica_id: str) -> Optional[str]:
        with self._lock:
            return self._urls.get(replica_id)

    def learn_url(self, replica_id: str, url: str) -> None:
        """Discovery WITHOUT liveness: remember where a replica can be
        reached (third-party views may teach us URLs, never leases)."""
        if not replica_id or not url or replica_id == self.replica_id:
            return
        with self._lock:
            self._urls.setdefault(replica_id, url)

    def known_urls(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._urls)

    def peers(self) -> List[Tuple[str, str]]:
        """Live (replica_id, url) pairs excluding self."""
        urls = {}
        with self._lock:
            urls = dict(self._urls)
        return [(rid, urls.get(rid, "")) for rid in self.live()
                if rid != self.replica_id and urls.get(rid)]

    def gossiped_freshness(self, shard: int) -> Optional[float]:
        """Last wall-clock scan stamp any peer reported for ``shard``
        — the takeover seed (the new owner is at LEAST this stale)."""
        with self._lock:
            return self._shard_fresh.get(shard)

    def note_epoch_if_changed(self) -> Tuple[bool, int, Tuple[str, ...]]:
        """Compare the current live set against the last observed one;
        bump the epoch on change. Returns (changed, epoch, live)."""
        alive = tuple(self.live())
        with self._lock:
            changed = alive != self._live_view
            if changed:
                self._live_view = alive
                self._epoch += 1
            return changed, self._epoch, alive

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def state(self) -> Dict[str, Any]:
        alive = self.live()
        with self._lock:
            urls = dict(self._urls)
            epoch = self._epoch
        return {
            "replica_id": self.replica_id,
            "url": self.url,
            "lease_s": self.lease_s,
            "epoch": epoch,
            "leader": alive[0] if alive else None,
            "is_leader": bool(alive) and alive[0] == self.replica_id,
            "live": alive,
            "known": sorted(urls),
        }
