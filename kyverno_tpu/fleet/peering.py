"""Verdict-cache peering — fetch-on-miss and async push, made safe by
content addressing.

A verdict-cache key (tpu/cache.py) is (policy-set content key,
resource content hash, request digest): a peer running an older or
newer policy revision holds entries under a DIFFERENT content key, so
a skewed peer can never satisfy a lookup — key mismatch is a miss by
construction, there is no invalidation protocol to get wrong. What
content addressing cannot rule out is a corrupted wire payload or a
lying peer, so every received column is re-verified on receipt:

- the echoed key must equal the requested key (a response for any
  other key is rejected, reason=key_mismatch);
- the column checksum (sha256 over key + raw bytes) must verify
  (truncated/bit-flipped payloads reject, reason=checksum);
- the column length must match the requester's compiled rule count
  (reason=shape) and decode cleanly (reason=decode).

A rejected entry counts on kyverno_fleet_peer_rejects_total and is
treated as a MISS — the ladder falls through to local compute, never
to a wrong verdict. Every remote call runs through a per-peer circuit
breaker and inside a deadline budget with jittered retry
(resilience/), so a dead peer costs one bounded timeout and then
nothing at all until its breaker half-opens.
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..resilience.breaker import CircuitBreaker
from ..resilience.faults import global_faults
from ..resilience.retry import Deadline, RetryPolicy, retry_call

CacheKey = Tuple[str, str, str]


def column_checksum(key: CacheKey, raw: bytes) -> str:
    """Checksum binding a column's bytes to its content-addressed key
    — shared by sender and receiver, so a payload that was truncated,
    spliced, or re-keyed in flight cannot verify."""
    h = hashlib.sha256()
    for part in key:
        h.update(part.encode("utf-8", "surrogatepass"))
        h.update(b"\x00")
    h.update(raw)
    return h.hexdigest()[:16]


def encode_entry(key: CacheKey, column: np.ndarray) -> Dict[str, Any]:
    raw = np.ascontiguousarray(column, dtype=np.int32).tobytes()
    return {"k": list(key), "c": base64.b64encode(raw).decode("ascii"),
            "n": int(column.shape[0]), "sha": column_checksum(key, raw)}


def decode_entry(doc: Dict[str, Any], want_key: Optional[CacheKey] = None,
                 expect_rows: Optional[int] = None,
                 ) -> Tuple[Optional[CacheKey], Optional[np.ndarray], str]:
    """Verify + decode one wire entry. Returns (key, column, reason)
    — column None and a reject reason when verification fails."""
    try:
        key = tuple(doc["k"])
        if len(key) != 3 or not all(isinstance(p, str) for p in key):
            return None, None, "decode"
        raw = base64.b64decode(doc["c"], validate=True)
        n = int(doc["n"])
        sha = doc["sha"]
    except (KeyError, TypeError, ValueError):
        return None, None, "decode"
    if want_key is not None and key != tuple(want_key):
        return key, None, "key_mismatch"
    if column_checksum(key, raw) != sha:
        return key, None, "checksum"
    if len(raw) != n * 4 or (expect_rows is not None and n != expect_rows):
        return key, None, "shape"
    col = np.frombuffer(raw, dtype=np.int32).copy()
    return key, col, ""


def _http_post_json(url: str, path: str, doc: Dict[str, Any],
                    timeout_s: float) -> Dict[str, Any]:
    """One JSON POST to a peer base url (http://127.0.0.1:PORT)."""
    import http.client
    from urllib.parse import urlparse

    parsed = urlparse(url)
    conn = http.client.HTTPConnection(parsed.hostname,
                                      parsed.port or 80,
                                      timeout=max(timeout_s, 0.05))
    try:
        conn.request("POST", path, json.dumps(doc),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise ConnectionError(f"peer {path} -> {resp.status}")
        return json.loads(body)
    finally:
        conn.close()


class PeerLink:
    """One peer: its URL, breaker, and call plumbing. The breaker is
    the degradation valve — once a peer has failed ``failure_threshold``
    consecutive calls every further interaction skips it instantly
    until the reset timeout half-opens one probe."""

    def __init__(self, replica_id: str, url: str,
                 failure_threshold: int = 2, reset_timeout_s: float = 5.0):
        self.replica_id = replica_id
        self.url = url
        self.breaker = CircuitBreaker(
            name=f"fleet:{replica_id}",
            failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s)

    # a call that SUCCEEDS but eats this fraction of its budget counts
    # as a breaker failure anyway: a slow-but-responsive peer (GC
    # pressure, CPU contention) must demote to local compute exactly
    # like a dead one, or every admission miss pays its latency —
    # the result is still used, only the peer's standing suffers
    SLOW_FRACTION = 0.8

    def call(self, path: str, doc: Dict[str, Any], budget_s: float,
             site: str, payload: Any = None,
             use_breaker: bool = True) -> Optional[Dict[str, Any]]:
        """POST under the breaker + one jittered retry inside the
        budget. None when the breaker is open or the call failed —
        callers degrade, they never raise to the serving path.

        ``use_breaker=False`` is the CONTROL-PLANE mode (heartbeats):
        already rate-limited by the heartbeat interval and bounded by
        the budget, they neither consult nor feed the breaker — a
        cheap succeeding heartbeat must not reset the consecutive-
        failure count of a broken data plane, and an open breaker
        must not mute heartbeats into a false failover."""
        if use_breaker and not self.breaker.allow():
            return None
        # trace propagation: if this call happens inside a span (an
        # admission-path peer fetch under admission.submit, a traced
        # heartbeat), ship the SpanContext in the envelope so the
        # receiver's handler joins OUR trace — one connected trace
        # across replicas. No active span, no envelope.
        try:
            from ..observability.tracing import (context_to_wire,
                                                 global_tracer)

            wire = context_to_wire(global_tracer.current_context())
            if wire is not None:
                doc = dict(doc)
                doc["trace"] = wire
        except Exception:
            pass
        deadline = Deadline(budget_s)
        t0 = time.monotonic()
        try:
            global_faults.fire(site, payload)
            out = retry_call(
                lambda: _http_post_json(self.url, path, doc,
                                        min(budget_s,
                                            deadline.remaining())),
                policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                   max_delay_s=0.05,
                                   deadline_s=budget_s),
                deadline=deadline, site=site)
            if use_breaker:
                if time.monotonic() - t0 > budget_s * self.SLOW_FRACTION:
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()
            return out
        except Exception:
            if use_breaker:
                self.breaker.record_failure()
            return None


class PushQueue:
    """Bounded queue of freshly computed (key, column) pairs awaiting
    async push to peers. Overflow drops the OLDEST entry (newest
    columns are the hottest) and counts the drop — backpressure must
    never reach the verdict-cache put path."""

    def __init__(self, maxlen: int = 4096, metrics=None):
        self._lock = threading.Lock()
        self._q: deque = deque(maxlen=maxlen)  # guarded-by: _lock
        self._metrics = metrics

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    def offer(self, key: CacheKey, column: np.ndarray) -> None:
        with self._lock:
            dropped = len(self._q) == self._q.maxlen
            self._q.append((key, np.array(column, dtype=np.int32,
                                          copy=True)))
        if dropped:
            self._registry().fleet_gossip.inc({"outcome": "dropped"})

    def drain(self, max_batch: int = 256
              ) -> List[Tuple[CacheKey, np.ndarray]]:
        out: List[Tuple[CacheKey, np.ndarray]] = []
        with self._lock:
            while self._q and len(out) < max_batch:
                out.append(self._q.popleft())
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class PeerCacheClient:
    """Fetch-on-miss + push across a set of PeerLinks. Links are
    created lazily per live peer and remembered (breaker state must
    survive membership flaps, or a flapping peer resets its own
    penalty)."""

    def __init__(self, metrics=None, fetch_budget_s: float = 0.15,
                 scan_fetch_budget_s: float = 1.0):
        self.fetch_budget_s = fetch_budget_s
        self.scan_fetch_budget_s = scan_fetch_budget_s
        self._lock = threading.Lock()
        self._links: Dict[str, PeerLink] = {}  # guarded-by: _lock
        self._metrics = metrics

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    def link(self, replica_id: str, url: str) -> PeerLink:
        with self._lock:
            lk = self._links.get(replica_id)
            if lk is None or lk.url != url:
                lk = PeerLink(replica_id, url)
                self._links[replica_id] = lk
            return lk

    def rekey(self, old_key: str, replica_id: str, url: str) -> PeerLink:
        """Discovery resolved a URL-keyed link to its real replica id:
        drop the provisional entry so breaker_states() (and the
        breaker-state metric family) never carry a stale duplicate."""
        with self._lock:
            self._links.pop(old_key, None)
        return self.link(replica_id, url)

    def links_for(self, peers: Sequence[Tuple[str, str]]) -> List[PeerLink]:
        return [self.link(rid, url) for rid, url in peers]

    # -- fetch

    def fetch(self, peers: Sequence[Tuple[str, str]],
              keys: Sequence[CacheKey], expect_rows: int,
              budget_s: Optional[float] = None,
              ) -> Dict[CacheKey, np.ndarray]:
        """Batch fetch: ask each live peer for the still-missing keys
        until everything resolved or the budget is gone. Rejected
        entries count and stay missing."""
        m = self._registry()
        budget = self.scan_fetch_budget_s if budget_s is None else budget_s
        deadline = Deadline(budget)
        found: Dict[CacheKey, np.ndarray] = {}
        missing = [tuple(k) for k in keys]
        for lk in self.links_for(peers):
            if not missing or deadline.expired():
                break
            resp = lk.call(
                "/fleet/fetch", {"keys": [list(k) for k in missing]},
                min(budget, deadline.remaining()),
                site="fleet.peer_fetch", payload=lk.replica_id)
            if resp is None:
                m.fleet_peer_fetch.inc({"peer": lk.replica_id,
                                        "outcome": "error"},
                                       value=len(missing))
                continue
            got: Dict[CacheKey, np.ndarray] = {}
            missing_set = set(missing)
            for doc in resp.get("entries", ()):
                key, col, reason = decode_entry(doc,
                                               expect_rows=expect_rows)
                if col is None:
                    m.fleet_peer_rejects.inc({"reason": reason or "decode"})
                    m.fleet_peer_fetch.inc({"peer": lk.replica_id,
                                            "outcome": "rejected"})
                    continue
                if key not in missing_set:
                    # an answer we never asked for is a lying peer
                    m.fleet_peer_rejects.inc({"reason": "key_mismatch"})
                    m.fleet_peer_fetch.inc({"peer": lk.replica_id,
                                            "outcome": "rejected"})
                    continue
                got[key] = col
            if got:
                m.fleet_peer_fetch.inc({"peer": lk.replica_id,
                                        "outcome": "hit"}, value=len(got))
            misses = len(missing) - len(got)
            if misses:
                m.fleet_peer_fetch.inc({"peer": lk.replica_id,
                                        "outcome": "miss"}, value=misses)
            found.update(got)
            missing = [k for k in missing if k not in found]
        return found

    def fetch_one(self, peers: Sequence[Tuple[str, str]], key: CacheKey,
                  expect_rows: int) -> Optional[np.ndarray]:
        """Single-key fetch for the admission submit path — the tight
        budget (one bounded peer timeout) is the p99 envelope
        guarantee when every peer is down."""
        got = self.fetch(peers, [key], expect_rows,
                         budget_s=self.fetch_budget_s)
        return got.get(tuple(key))

    # -- push

    def push(self, peers: Sequence[Tuple[str, str]],
             entries: Sequence[Tuple[CacheKey, np.ndarray]]) -> int:
        """Fire one /fleet/push of ``entries`` at every live peer.
        Returns the number of peer sends that succeeded."""
        if not entries:
            return 0
        m = self._registry()
        doc = {"entries": [encode_entry(k, c) for k, c in entries]}
        sent = 0
        for lk in self.links_for(peers):
            resp = lk.call("/fleet/push", doc, self.scan_fetch_budget_s,
                           site="fleet.gossip", payload=lk.replica_id)
            if resp is None:
                m.fleet_gossip.inc({"outcome": "error"})
            else:
                sent += 1
                m.fleet_gossip.inc({"outcome": "sent"},
                                   value=len(entries))
        return sent

    def breaker_states(self) -> Dict[str, str]:
        with self._lock:
            links = list(self._links.values())
        return {lk.replica_id: lk.breaker.state for lk in links}
