"""The localhost peer-protocol endpoint every replica exposes.

Four POST routes, all JSON, all answerable from local state only —
a peer request never triggers compute, compilation, or another remote
call, so the peer protocol cannot amplify load across the fleet:

- ``/fleet/heartbeat``: renew the sender's membership lease; the
  response carries our own view (anti-entropy for URL discovery) and,
  from the leader, the gossiped fleet telemetry rollup.
- ``/fleet/fetch``: look up a batch of content-addressed verdict
  keys in the LOCAL cache; hits are returned checksummed. A key we
  do not hold is simply absent from the response.
- ``/fleet/push``: accept freshly computed columns from a peer.
  Every entry is checksum-verified BEFORE it lands in the local cache
  (a poisoned push is dropped and counted, exactly like a poisoned
  fetch response on the client side).
- ``/fleet/telemetry``: this replica's sealed telemetry snapshot
  (fleet/telemetry.py) — the leader pulls it on the heartbeat
  cadence. Also served on GET for humans and scripts.

Every POST body may carry the caller's trace context in a ``trace``
envelope (injected by ``PeerLink.call``); when present, the handler
runs inside a ``fleet.rpc.*`` child span so a cross-replica exchange
renders as ONE connected trace. An envelope-free request (old peer,
curl) opens no span — untraced traffic stays span-free.

GET ``/fleet/state`` returns the membership/shard/telemetry view
(also exposed as ``/debug/fleet`` on the serving debug router).
"""

from __future__ import annotations

import contextlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING, Any, Dict

from ..observability.tracing import context_from_wire, global_tracer
from ..resilience.faults import SITE_FLEET_TELEMETRY, global_faults
from .peering import decode_entry, encode_entry

if TYPE_CHECKING:  # pragma: no cover
    from .manager import FleetManager


def _rpc_span(route: str, doc: Dict[str, Any], replica_id: str):
    """Child span for a traced peer RPC, no-op context otherwise. The
    ``trace`` envelope is POPPED so route handlers never see transport
    framing in their payload."""
    ctx = context_from_wire(doc.pop("trace", None)) \
        if isinstance(doc, dict) else None
    if ctx is None:
        return contextlib.nullcontext()
    return global_tracer.span(
        f"fleet.rpc.{route}", parent=ctx, replica=replica_id,
        caller=str(doc.get("replica_id", "")) if isinstance(doc, dict)
        else "")


class FleetPeerServer:
    """ThreadingHTTPServer wrapper bound to 127.0.0.1 — the peer
    protocol is an intra-host (or tunneled) control surface, never an
    internet-facing one."""

    def __init__(self, manager: "FleetManager", port: int = 0):
        mgr = manager

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, doc: Dict[str, Any]) -> None:
                body = (json.dumps(doc) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/fleet/state":
                    self._send(200, mgr.state())
                elif self.path == "/fleet/telemetry":
                    self._send(200, _handle_telemetry(mgr))
                elif self.path == "/healthz":
                    self._send(200, {"ok": True})
                else:
                    self._send(404, {"error": "unknown path"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    doc = json.loads(self.rfile.read(length))
                except ValueError:
                    self._send(400, {"error": "bad json"})
                    return
                routes = {
                    "/fleet/heartbeat": ("heartbeat", mgr.on_heartbeat),
                    "/fleet/fetch": ("fetch",
                                     lambda d: _handle_fetch(mgr, d)),
                    "/fleet/push": ("push",
                                    lambda d: _handle_push(mgr, d)),
                    "/fleet/telemetry": ("telemetry",
                                         lambda d: _handle_telemetry(mgr)),
                }
                hit = routes.get(self.path)
                if hit is None:
                    self._send(404, {"error": "unknown path"})
                    return
                route, handler = hit
                with _rpc_span(route, doc, mgr.config.replica_id):
                    self._send(200, handler(doc))

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Req)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fleet-peer-server")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def _handle_fetch(mgr: "FleetManager", doc: Dict[str, Any]
                  ) -> Dict[str, Any]:
    """Local-cache-only lookup of a key batch; capped so one request
    cannot serialize an unbounded response."""
    entries = []
    keys = doc.get("keys") or ()
    for raw in list(keys)[:mgr.config.fetch_max_keys]:
        try:
            key = tuple(raw)
            if len(key) != 3:
                continue
        except TypeError:
            continue
        col = mgr.cache_peek(key)
        if col is not None:
            entries.append(encode_entry(key, col))
    return {"replica_id": mgr.config.replica_id, "entries": entries}


def _handle_push(mgr: "FleetManager", doc: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """Verify-then-store for pushed columns: the receive side runs the
    SAME verification ladder as the fetch client — a peer cannot
    poison us just because it did the pushing."""
    from ..observability.metrics import global_registry as m

    accepted = rejected = 0
    for raw in (doc.get("entries") or ())[:mgr.config.fetch_max_keys]:
        key, col, reason = decode_entry(raw,
                                        expect_rows=mgr.expected_rows())
        if col is None:
            rejected += 1
            m.fleet_peer_rejects.inc({"reason": reason or "decode"})
            continue
        mgr.cache_store(key, col)
        accepted += 1
    if accepted:
        m.fleet_gossip.inc({"outcome": "received"}, value=accepted)
    return {"replica_id": mgr.config.replica_id,
            "accepted": accepted, "rejected": rejected}


def _handle_telemetry(mgr: "FleetManager") -> Dict[str, Any]:
    """This replica's sealed telemetry snapshot. The fault filter sits
    on the OUTGOING doc — a ``fleet.telemetry:corrupt`` chaos rule
    ships a damaged snapshot whose checksum then fails on the puller,
    exercising the aggregator's reject path end to end."""
    return global_faults.corrupt(SITE_FLEET_TELEMETRY,
                                 mgr.telemetry.build())
