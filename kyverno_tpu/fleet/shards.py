"""Rendezvous-hash shard assignment over the resource keyspace.

The keyspace is split into a fixed number of shards (uid -> shard by
stable hash); each shard is owned by exactly one live replica, chosen
by highest-random-weight (rendezvous) hashing. Two properties make
this the right primitive for failover:

- **determinism**: every replica with the same live-membership view
  computes the same assignment — no assignment state to replicate,
  the lease ledger IS the assignment input;
- **minimal movement**: when a replica dies, only ITS shards change
  owner (each surviving shard's argmax is unchanged by removing a
  non-winning candidate), so a failover never reshuffles the warm
  majority of the fleet.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

DEFAULT_NUM_SHARDS = 64


def shard_of(uid: str, num_shards: int = DEFAULT_NUM_SHARDS) -> int:
    """Stable shard index for a resource uid (or any string key)."""
    h = hashlib.sha256(uid.encode("utf-8", "surrogatepass")).digest()
    return int.from_bytes(h[:8], "big") % max(num_shards, 1)


def _score(shard: int, replica_id: str) -> int:
    h = hashlib.sha256(f"{shard}:{replica_id}".encode()).digest()
    return int.from_bytes(h[:8], "big")


def rendezvous_owner(shard: int,
                     replicas: Sequence[str]) -> Optional[str]:
    """The live replica owning ``shard`` — highest rendezvous score
    wins (ties broken by replica id so the result is total)."""
    if not replicas:
        return None
    return max(replicas, key=lambda rid: (_score(shard, rid), rid))


def assign_shards(replicas: Sequence[str],
                  num_shards: int = DEFAULT_NUM_SHARDS
                  ) -> Dict[int, Optional[str]]:
    """Full shard -> owner map for a live set."""
    return {s: rendezvous_owner(s, replicas) for s in range(num_shards)}


def owned_shards(replica_id: str, replicas: Sequence[str],
                 num_shards: int = DEFAULT_NUM_SHARDS) -> List[int]:
    """The shards ``replica_id`` owns under the given live set."""
    return [s for s in range(num_shards)
            if rendezvous_owner(s, replicas) == replica_id]
