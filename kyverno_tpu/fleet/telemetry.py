"""Fleet telemetry plane — cross-replica observability that composes
with the same never-wrong discipline the peered verdict caches use.

Every replica serves a structured, checksummed **snapshot** of its own
telemetry over ``/fleet/telemetry``: lifetime monotonic counters
(admissions, slow admissions, scan ticks, shadow-verification checks
and divergences), per-window SLO sample counts, and a few health
gauges — stamped with the replica id, a per-boot nonce, a monotonic
sequence number, the membership epoch, and a wall-clock timestamp,
then sealed with a sha256 checksum over the canonical JSON body.

The fleet **leader** (the existing lowest-live-id bit) pulls peers on
the heartbeat cadence and folds snapshots through a trust ladder:

1. **checksum** — the canonical-JSON sha must verify (a truncated,
   tampered, or bit-flipped snapshot rejects here);
2. **schema_version** — a replica speaking a different telemetry
   schema (rolling upgrade) is dropped, not misparsed;
3. **replay/ordering** — within one boot the sequence number must
   advance and the epoch must not regress (a replayed or reordered
   snapshot cannot rewind the view); a NEW boot id resets both;
4. **staleness** — a snapshot older than ``max_age_s`` is history,
   not state.

A snapshot that fails any rung is dropped and counted on
``kyverno_fleet_telemetry_rejects_total{reason}`` — never merged
wrong. Accepted counters merge as **deltas**: the fold adds
``current - last_seen`` (or ``current`` after a reset, detected by a
new boot id or a value that went backwards), so a replica restarting
with zeroed counters can never drive a fleet aggregate backwards and
the running total equals the ground-truth work the fleet actually
did, including work a dead replica finished before it died.

The leader publishes the fold as the ``kyverno_fleet_agg_*`` families
plus a fleet-wide SLO burn computed over the merged window samples
(sum of slow over sum of requests — a weighted merge, not an average
of per-replica averages), and gossips the rollup document back on the
heartbeat exchange so ANY replica can answer ``/debug/fleet`` with
the fleet-level view.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

TELEMETRY_SCHEMA_VERSION = 1

# names a snapshot's counters section may carry; the aggregator folds
# exactly these (an unknown name in a verified snapshot is ignored, so
# a newer replica adding counters stays mergeable by an older leader)
COUNTER_NAMES = ("admission_requests", "admission_slow", "scan_ticks",
                 "verification_checked", "verification_divergences")

# counter name -> aggregate family attribute on the registry
_AGG_FAMILY = {
    "admission_requests": "fleet_agg_admissions",
    "admission_slow": "fleet_agg_admission_slow",
    "scan_ticks": "fleet_agg_scan_ticks",
    "verification_checked": "fleet_agg_verification_checked",
    "verification_divergences": "fleet_agg_divergence",
}


def snapshot_checksum(doc: Dict[str, Any]) -> str:
    """Checksum over the canonical JSON of everything but the seal
    itself — any field mutated, dropped, or spliced in flight fails
    verification (the column_checksum idea applied to a document)."""
    body = {k: v for k, v in doc.items() if k != "sha"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"),
                       default=str)
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:16]


class TelemetrySource:
    """Builds this replica's telemetry snapshots. The sequence number
    is monotonic per boot; the boot nonce is what lets an aggregator
    tell a legitimate restart (new boot id, seq back at 1) from a
    replayed old snapshot (same boot id, seq going backwards)."""

    def __init__(self, manager, slo=None, verifier=None):
        self._manager = manager
        self._slo = slo
        self._verifier = verifier
        self._lock = threading.Lock()
        self._seq = 0                                # guarded-by: _lock
        self.boot_id = os.urandom(4).hex()
        # test/bench hooks: override where counters / window samples
        # come from (in-process multi-replica tests share the process
        # globals, so per-replica ground truth needs injection)
        self.counters_provider: Optional[Callable[[], Dict[str, Any]]] = None
        self.windows_provider: Optional[Callable[[], Dict[str, Any]]] = None

    def _slo_tracker(self):
        if self._slo is None:
            from ..observability.analytics import global_slo

            self._slo = global_slo
        return self._slo

    def _verifier_ref(self):
        if self._verifier is None:
            from ..observability.verification import global_verifier

            self._verifier = global_verifier
        return self._verifier

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def counters(self) -> Dict[str, float]:
        if self.counters_provider is not None:
            return dict(self.counters_provider())
        out: Dict[str, float] = dict(
            self._slo_tracker().telemetry_counters())
        try:
            v = self._verifier_ref().totals()
            out["verification_checked"] = v["checked"]
            out["verification_divergences"] = v["divergences"]
        except Exception:
            out.setdefault("verification_checked", 0)
            out.setdefault("verification_divergences", 0)
        return out

    def _windows(self) -> Dict[str, Any]:
        if self.windows_provider is not None:
            return dict(self.windows_provider())
        try:
            return self._slo_tracker().telemetry_windows()
        except Exception:
            return {}

    def _gauges(self) -> Dict[str, Any]:
        mgr = self._manager
        hit_rate = None
        try:
            fn = getattr(mgr.cache, "hit_rate", None)
            if fn is not None:
                hit_rate = round(float(fn()), 4)
        except Exception:
            hit_rate = None
        return {
            "shards_owned": len(mgr.owned_view()),
            "cache_hit_rate": hit_rate,
        }

    def build(self) -> Dict[str, Any]:
        """One sealed snapshot of this replica's telemetry — the
        ``/fleet/telemetry`` response body. Everything read here is
        local state; building a snapshot never triggers compute or a
        remote call (the no-amplification rule of the peer protocol)."""
        mgr = self._manager
        with self._lock:
            self._seq += 1
            seq = self._seq
        doc: Dict[str, Any] = {
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "replica_id": mgr.config.replica_id,
            "boot_id": self.boot_id,
            "seq": seq,
            "epoch": mgr.membership.epoch,
            "at": round(time.time(), 6),
            "counters": self.counters(),
            "slo_windows": self._windows(),
            "gauges": self._gauges(),
        }
        doc["sha"] = snapshot_checksum(doc)
        return doc


class TelemetryAggregator:
    """Leader-side fold of replica snapshots into fleet aggregates.

    Per replica the aggregator remembers the last accepted (boot id,
    seq, epoch, counter values); counters merge as deltas with reset
    detection, so the running totals are monotonic by construction.
    ``prune()`` drops replicas that left the live set from the health
    matrix and the per-replica gauge series — their already-folded
    contribution stays in the totals (work that happened, happened)."""

    def __init__(self, metrics=None, clock=time.monotonic,
                 max_age_s: float = 30.0):
        self._metrics = metrics
        self._clock = clock
        self.max_age_s = max_age_s
        self._lock = threading.Lock()
        self._replicas: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._totals: Dict[str, float] = {}             # guarded-by: _lock
        self._rejects: Dict[str, int] = {}              # guarded-by: _lock

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    # -- ingest (the trust ladder)

    def ingest(self, doc: Any) -> str:
        """Fold one snapshot. Returns "" on acceptance or the reject
        reason; a rejected snapshot is counted and changes NOTHING."""
        reason, deltas = self._verify_and_fold(doc)
        if reason:
            m = self._registry()
            m.fleet_telemetry_rejects.inc({"reason": reason})
            with self._lock:
                self._count_reject_locked(reason)
            return reason
        if deltas:
            m = self._registry()
            for name, delta in deltas.items():
                fam = _AGG_FAMILY.get(name)
                if fam is not None and delta:
                    getattr(m, fam).inc(value=delta)
        return ""

    def _verify_and_fold(self, doc: Any
                         ) -> Tuple[str, Optional[Dict[str, float]]]:
        # rung 0: shape — a non-document can't even reach the checksum
        if not isinstance(doc, dict):
            return "decode", None
        sha = doc.get("sha")
        rid = doc.get("replica_id")
        counters = doc.get("counters")
        if not isinstance(sha, str) or not isinstance(rid, str) \
                or not rid or not isinstance(counters, dict):
            return "decode", None
        # rung 1: checksum — nothing below may trust a field until the
        # seal verifies (a tampered reason field must not pick its own
        # reject reason)
        if snapshot_checksum(doc) != sha:
            return "checksum", None
        # rung 2: schema — a rolling upgrade speaking a different
        # telemetry schema is dropped whole, never half-parsed
        if doc.get("schema_version") != TELEMETRY_SCHEMA_VERSION:
            return "schema_version", None
        try:
            boot_id = str(doc.get("boot_id") or "")
            seq = int(doc["seq"])
            epoch = int(doc.get("epoch", 0))
            at = float(doc["at"])
            vals = {n: float(counters.get(n, 0.0)) for n in COUNTER_NAMES
                    if isinstance(counters.get(n, 0.0), (int, float))}
        except (KeyError, TypeError, ValueError):
            return "decode", None
        # rung 4 (staleness) checked before taking the lock — it needs
        # no per-replica state
        if self.max_age_s > 0 and time.time() - at > self.max_age_s:
            return "stale", None
        now = self._clock()
        with self._lock:
            prev = self._replicas.get(rid)
            same_boot = prev is not None and prev["boot_id"] == boot_id
            # rung 3: replay/ordering — within one boot, seq must
            # advance and epoch must not regress
            if same_boot and seq <= prev["seq"]:
                return "stale_seq", None
            if same_boot and epoch < prev["epoch"]:
                return "epoch", None
            deltas: Dict[str, float] = {}
            for name, cur in vals.items():
                last = prev["counters"].get(name, 0.0) if same_boot else 0.0
                # reset detection: a value that went backwards within a
                # boot (or any value after a restart) folds as the full
                # current value — the delta is never negative, so the
                # aggregate is monotonic by construction
                delta = cur - last if cur >= last else cur
                if delta:
                    deltas[name] = delta
                    self._totals[name] = self._totals.get(name, 0.0) + delta
            self._replicas[rid] = {
                "boot_id": boot_id, "seq": seq, "epoch": epoch, "at": at,
                "counters": vals,
                "windows": dict(doc.get("slo_windows") or {}),
                "gauges": dict(doc.get("gauges") or {}),
                "received": now,
            }
        return "", deltas

    def _count_reject_locked(self, reason: str) -> None:
        self._rejects[reason] = self._rejects.get(reason, 0) + 1

    def note_reject(self, reason: str) -> None:
        with self._lock:
            self._count_reject_locked(reason)

    def prune(self, live_ids) -> None:
        """Drop replicas that left the live set: they disappear from
        the health matrix and their per-replica gauge series is
        removed (label cardinality tracks the LIVE fleet), while their
        folded contribution stays in the totals."""
        live = set(live_ids)
        with self._lock:
            gone = [rid for rid in self._replicas if rid not in live]
            for rid in gone:
                del self._replicas[rid]
        if gone:
            m = self._registry()
            for rid in gone:
                m.fleet_agg_snapshot_age.remove({"replica": rid})

    # -- read side

    def totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._totals)

    def rejects(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._rejects)

    def rollup(self, computed_by: str, epoch: int,
               slo_config=None) -> Dict[str, Any]:
        """The fleet-level document: per-replica health matrix + merged
        totals + fleet SLO burn. The leader computes it once per pull
        round and gossips it back on heartbeats, so any replica can
        serve it from /debug/fleet."""
        if slo_config is None:
            from ..observability.analytics import global_slo

            slo_config = global_slo.config
        budget = max(getattr(slo_config, "admission_error_budget", 0.01),
                     1e-9)
        now = self._clock()
        with self._lock:
            replicas = {rid: dict(rec) for rid, rec in
                        self._replicas.items()}
            totals = dict(self._totals)
            rejects = dict(self._rejects)
        matrix: Dict[str, Any] = {}
        merged_windows: Dict[str, Dict[str, float]] = {}
        for rid, rec in sorted(replicas.items()):
            windows = rec.get("windows") or {}
            burn = None
            for _name, w in sorted(windows.items()):
                req = float(w.get("requests", 0) or 0)
                slow = float(w.get("slow", 0) or 0)
                if burn is None:  # matrix shows the SHORTEST window
                    burn = round((slow / req) / budget, 4) if req else 0.0
            for name, w in windows.items():
                agg = merged_windows.setdefault(
                    name, {"requests": 0.0, "slow": 0.0,
                           "divergences": 0.0})
                agg["requests"] += float(w.get("requests", 0) or 0)
                agg["slow"] += float(w.get("slow", 0) or 0)
                agg["divergences"] += float(w.get("divergences", 0) or 0)
            gauges = rec.get("gauges") or {}
            matrix[rid] = {
                "seq": rec["seq"],
                "epoch": rec["epoch"],
                "snapshot_age_s": round(max(0.0, now - rec["received"]), 3),
                "slo_burn": burn if burn is not None else 0.0,
                "divergences": rec["counters"].get(
                    "verification_divergences", 0.0),
                "admission_requests": rec["counters"].get(
                    "admission_requests", 0.0),
                "shards_owned": gauges.get("shards_owned"),
                "cache_hit_rate": gauges.get("cache_hit_rate"),
                "windows": windows,
            }
        burn_by_window = {
            name: (round((w["slow"] / w["requests"]) / budget, 4)
                   if w["requests"] else 0.0)
            for name, w in sorted(merged_windows.items())}
        degraded = totals.get("verification_divergences", 0.0) > 0
        return {
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "computed_by": computed_by,
            "epoch": epoch,
            "at": round(time.time(), 6),
            "replicas": matrix,
            "totals": totals,
            "burn": burn_by_window,
            "merged_windows": merged_windows,
            "degraded": degraded,
            "rejects": rejects,
        }

    def publish_gauges(self) -> None:
        """Refresh the leader-side aggregate gauges (the counters were
        already advanced delta-by-delta at ingest)."""
        m = self._registry()
        now = self._clock()
        with self._lock:
            replicas = {rid: rec["received"]
                        for rid, rec in self._replicas.items()}
            totals = dict(self._totals)
        fresh = 0
        for rid, received in sorted(replicas.items()):
            age = max(0.0, now - received)
            m.fleet_agg_snapshot_age.set(round(age, 3), {"replica": rid})
            if self.max_age_s <= 0 or age <= self.max_age_s:
                fresh += 1
        m.fleet_agg_replicas_reporting.set(fresh)
        m.fleet_agg_degraded.set(
            1.0 if totals.get("verification_divergences", 0.0) > 0 else 0.0)

    def publish_burn(self, rollup: Dict[str, Any]) -> None:
        m = self._registry()
        for name, rate in (rollup.get("burn") or {}).items():
            m.fleet_agg_burn.set(float(rate), {"window": str(name)})
