"""GlobalContextEntry subsystem.

Reference parity: api/kyverno/v2alpha1/global_context_entry_types.go
(CRD model), pkg/globalcontext/store/store.go (entry store),
pkg/controllers/globalcontext (reconciler), with the two entry kinds:

- ``kubernetesResource``: a live projection of cluster resources
  (group/version/resource[/namespace]) kept current by subscribing to
  the ClusterSnapshot — the snapshot IS this framework's watch layer
  (pkg/globalcontext/k8sresource/entry.go uses informers);
- ``apiCall``: an external call polled on ``refreshInterval``
  (pkg/globalcontext/externalapi/entry.go), executed through a
  pluggable executor so tests/air-gapped runs stay offline.

The store plugs into the engine as ``DataSources.global_context``
(mapping protocol): a missing or errored entry raises at rule
evaluation time, matching the reference's invalid-entry behavior
(pkg/globalcontext/invalid/entry.go)."""

from .entry import EntryError, ExternalApiEntry, KubernetesResourceEntry
from .store import GlobalContextStore
from .types import GlobalContextEntry

__all__ = ["GlobalContextStore", "GlobalContextEntry", "EntryError",
           "KubernetesResourceEntry", "ExternalApiEntry"]
