"""Store entries (pkg/globalcontext/{k8sresource,externalapi}/entry.go).

Both expose ``get() -> data | raise EntryError``. The k8s-resource
entry projects the ClusterSnapshot live (subscription keeps a uid set
current); the external-API entry re-executes its call when the cached
result is older than refreshInterval, and serves the last error state
when the call keeps failing (invalid/entry.go semantics)."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .types import ExternalAPICallSpec, KubernetesResourceSpec


class EntryError(Exception):
    pass


class KubernetesResourceEntry:
    def __init__(self, spec: KubernetesResourceSpec, snapshot) -> None:
        self.spec = spec
        self.snapshot = snapshot
        self._lock = threading.Lock()
        self._uids: set = set()
        self._stopped = False
        snapshot.subscribe(self._on_change)
        # warm from current snapshot contents
        for uid, res, _ in snapshot.items():
            if self._matches(res):
                self._uids.add(uid)

    def _matches(self, res: Dict[str, Any]) -> bool:
        from ..vap.policy import kind_to_resource

        api_version = res.get("apiVersion", "")
        group, _, version = api_version.rpartition("/")
        if self.spec.group != group or (
                self.spec.version and self.spec.version != version):
            return False
        if kind_to_resource(res.get("kind", "")) != self.spec.resource:
            return False
        if self.spec.namespace:
            ns = (res.get("metadata") or {}).get("namespace", "")
            if ns != self.spec.namespace:
                return False
        return True

    def _on_change(self, uid: str, change: str) -> None:
        if self._stopped:
            return
        with self._lock:
            if change == "delete":
                self._uids.discard(uid)
                return
            res = self.snapshot.get(uid)
            if res is not None and self._matches(res):
                self._uids.add(uid)
            else:
                self._uids.discard(uid)

    def get(self) -> List[Dict[str, Any]]:
        if self._stopped:
            raise EntryError("entry stopped")
        with self._lock:
            uids = list(self._uids)
        out = []
        for uid in uids:
            res = self.snapshot.get(uid)
            if res is not None:
                out.append(res)
        return out

    def stop(self) -> None:
        self._stopped = True
        unsub = getattr(self.snapshot, "unsubscribe", None)
        if unsub is not None:
            unsub(self._on_change)


class ExternalApiEntry:
    """Polled API entry. ``executor(spec) -> data`` is the pluggable
    call (the reference goes through apicall.Execute with service URLs,
    apiCall.go:107); refresh happens lazily when the cached value is
    older than refreshInterval, and a ``refresh()`` hook exists for a
    background poller loop."""

    def __init__(self, spec: ExternalAPICallSpec,
                 executor: Callable[[ExternalAPICallSpec], Any],
                 clock=time.monotonic) -> None:
        self.spec = spec
        self.executor = executor
        self._clock = clock
        self._lock = threading.Lock()
        self._data: Any = None
        self._err: Optional[str] = None
        self._fetched_at: Optional[float] = None
        self._stopped = False

    def refresh(self) -> None:
        try:
            data = self.executor(self.spec)
            with self._lock:
                self._data = data
                self._err = None
                self._fetched_at = self._clock()
        except Exception as e:
            with self._lock:
                self._err = str(e)
                # a failed poll marks the entry stale-with-error but
                # keeps the timestamp so we don't hot-loop the executor
                self._fetched_at = self._clock()

    def _stale(self) -> bool:
        return (self._fetched_at is None
                or self._clock() - self._fetched_at >= self.spec.refresh_interval_s)

    def get(self) -> Any:
        if self._stopped:
            raise EntryError("entry stopped")
        with self._lock:
            stale = self._stale()
        if stale:
            self.refresh()
        with self._lock:
            if self._err is not None:
                raise EntryError(f"api call failed: {self._err}")
            return self._data

    def stop(self) -> None:
        self._stopped = True
