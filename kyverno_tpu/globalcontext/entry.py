"""Store entries (pkg/globalcontext/{k8sresource,externalapi}/entry.go).

Both expose ``get() -> data | raise EntryError``. The k8s-resource
entry projects the ClusterSnapshot live (subscription keeps a uid set
current); the external-API entry re-executes its call when the cached
result is older than refreshInterval, and serves the last error state
when the call keeps failing (invalid/entry.go semantics)."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .types import ExternalAPICallSpec, KubernetesResourceSpec


class EntryError(Exception):
    pass


class KubernetesResourceEntry:
    def __init__(self, spec: KubernetesResourceSpec, snapshot) -> None:
        self.spec = spec
        self.snapshot = snapshot
        self._lock = threading.Lock()
        self._uids: set = set()
        self._stopped = False
        snapshot.subscribe(self._on_change)
        # warm from current snapshot contents
        for uid, res, _ in snapshot.items():
            if self._matches(res):
                self._uids.add(uid)

    def _matches(self, res: Dict[str, Any]) -> bool:
        from ..vap.policy import kind_to_resource

        api_version = res.get("apiVersion", "")
        group, _, version = api_version.rpartition("/")
        if self.spec.group != group or (
                self.spec.version and self.spec.version != version):
            return False
        if kind_to_resource(res.get("kind", "")) != self.spec.resource:
            return False
        if self.spec.namespace:
            ns = (res.get("metadata") or {}).get("namespace", "")
            if ns != self.spec.namespace:
                return False
        return True

    def _on_change(self, uid: str, change: str) -> None:
        if self._stopped:
            return
        with self._lock:
            if change == "delete":
                self._uids.discard(uid)
                return
            res = self.snapshot.get(uid)
            if res is not None and self._matches(res):
                self._uids.add(uid)
            else:
                self._uids.discard(uid)

    def get(self) -> List[Dict[str, Any]]:
        if self._stopped:
            raise EntryError("entry stopped")
        with self._lock:
            uids = list(self._uids)
        out = []
        for uid in uids:
            res = self.snapshot.get(uid)
            if res is not None:
                out.append(res)
        return out

    def stop(self) -> None:
        self._stopped = True
        unsub = getattr(self.snapshot, "unsubscribe", None)
        if unsub is not None:
            unsub(self._on_change)


class ExternalApiEntry:
    """Polled API entry. ``executor(spec) -> data`` is the pluggable
    call (the reference goes through apicall.Execute with service URLs,
    apiCall.go:107); refresh happens lazily when the cached value is
    older than refreshInterval, and a ``refresh()`` hook exists for a
    background poller loop.

    Degradation ladder (invalid/entry.go semantics, resilience/):
    each refresh retries with jittered backoff inside a deadline
    budget; while refreshes keep failing the entry serves the
    last-known-good data until it is older than ``stale_ttl_s``
    (default 3x refreshInterval), after which ``get()`` surfaces the
    error state; a healed backend recovers the entry on the next poll."""

    STALE_TTL_FACTOR = 3.0

    def __init__(self, spec: ExternalAPICallSpec,
                 executor: Callable[[ExternalAPICallSpec], Any],
                 clock=time.monotonic,
                 retry=None,
                 stale_ttl_s: Optional[float] = None,
                 sleep=time.sleep) -> None:
        self.spec = spec
        self.executor = executor
        self._clock = clock
        self._sleep = sleep
        if retry is None:
            from ..resilience.retry import RetryPolicy

            # the refresh loop's budget must stay well inside the
            # refresh interval or a slow-failing backend makes polls
            # pile onto each other
            retry = RetryPolicy(
                max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
                deadline_s=min(5.0, max(spec.refresh_interval_s / 2.0, 0.1)))
        self.retry = retry
        self.stale_ttl_s = (stale_ttl_s if stale_ttl_s is not None
                            else self.STALE_TTL_FACTOR * spec.refresh_interval_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: Any = None                    # guarded-by: _lock
        self._err: Optional[str] = None           # guarded-by: _lock
        self._fetched_at: Optional[float] = None  # guarded-by: _lock  (last attempt)
        self._ok_at: Optional[float] = None       # guarded-by: _lock  (last success)
        # single-flight: one lazy refresh at a time
        self._refreshing = False                  # guarded-by: _lock
        self._stopped = False

    def refresh(self) -> None:
        from ..resilience.faults import SITE_GCTX_REFRESH, global_faults
        from ..resilience.retry import Deadline, retry_call

        def attempt():
            global_faults.fire(SITE_GCTX_REFRESH)
            return self.executor(self.spec)

        try:
            data = retry_call(
                attempt, policy=self.retry,
                deadline=Deadline(self.retry.deadline_s, clock=self._clock),
                site=SITE_GCTX_REFRESH, clock=self._clock, sleep=self._sleep)
            with self._lock:
                now = self._clock()
                self._data = data
                self._err = None
                self._fetched_at = now
                self._ok_at = now
        except Exception as e:
            with self._lock:
                self._err = str(e)
                # a failed poll marks the entry stale-with-error but
                # keeps the timestamp so we don't hot-loop the executor;
                # last-known-good data stays for the stale-serve window
                self._fetched_at = self._clock()

    def _stale_locked(self) -> bool:
        return (self._fetched_at is None
                or self._clock() - self._fetched_at >= self.spec.refresh_interval_s)

    def get(self) -> Any:
        if self._stopped:
            raise EntryError("entry stopped")
        # single-flight: exactly one reader pays the retry/backoff
        # budget per staleness window; everyone else serves the cached
        # (possibly stale) value immediately. Without this, M concurrent
        # admissions against a down backend each run their own retry
        # loop — M x deadline_s of added latency and 3M redundant calls
        # onto a backend that is already failing.
        do_refresh = False
        with self._cond:
            if self._stale_locked() and not self._refreshing:
                self._refreshing = True
                do_refresh = True
        if do_refresh:
            try:
                self.refresh()
            finally:
                with self._cond:
                    self._refreshing = False
                    self._cond.notify_all()
        with self._cond:
            # cold entry (never fetched): there is nothing to serve
            # stale, so wait for the in-flight first fetch to land
            # instead of handing back an empty result. wait_for bounds
            # the TOTAL wait (a bare wait() in a loop restarts its
            # timeout on every spurious wakeup): if the refresher is
            # wedged inside a hung executor past the retry budget, this
            # surfaces the error state instead of hanging every
            # admission thread that touches the entry
            # a deadline-free retry policy still gets a FINITE wait
            # here (the refresh interval, floored at 30s): an unbounded
            # cond.wait would let one hung executor wedge every
            # admission thread that touches the cold entry
            wait_s = (self.retry.deadline_s + 1.0
                      if self.retry.deadline_s is not None
                      else max(self.spec.refresh_interval_s, 30.0))
            if not self._cond.wait_for(
                    lambda: self._fetched_at is not None
                    or not self._refreshing,
                    timeout=wait_s):
                raise EntryError(
                    "api call failed: first fetch still in flight past "
                    "the retry deadline budget")
            if self._err is None:
                return self._data
            # serve last-known-good while it is younger than the TTL:
            # a flapping backend degrades reads to slightly-stale data
            # instead of erroring every admission that touches it
            if (self._ok_at is not None
                    and self._clock() - self._ok_at < self.stale_ttl_s):
                return self._data
            raise EntryError(f"api call failed: {self._err}")

    def stop(self) -> None:
        self._stopped = True
