"""Entry store + reconciler (pkg/globalcontext/store/store.go,
pkg/controllers/globalcontext/controller.go).

``GlobalContextStore`` implements the mapping protocol the engine's
``globalReference`` context loader consumes
(engine/contextloaders.py _load_global): ``name in store`` and
``store[name]``, where a present-but-failing entry raises EntryError
so rules surface a context-load error rather than silently evaluating
against stale data."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from .entry import EntryError, ExternalApiEntry, KubernetesResourceEntry
from .types import GlobalContextEntry


class GlobalContextStore:
    def __init__(self, snapshot=None,
                 api_executor: Optional[Callable] = None) -> None:
        self.snapshot = snapshot
        self.api_executor = api_executor
        self._lock = threading.Lock()
        self._entries: Dict[str, Any] = {}  # guarded-by: _lock

    # -- store protocol (store.go:24)

    def set(self, key: str, entry) -> None:
        with self._lock:
            old = self._entries.get(key)
            self._entries[key] = entry
            if old is not None:
                old.stop()

    def get_entry(self, key: str):
        with self._lock:
            return self._entries.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
        if entry is not None:
            entry.stop()

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    # -- reconciler (controllers/globalcontext/controller.go)

    def apply(self, doc_or_entry) -> List[str]:
        """Reconcile one GlobalContextEntry CR into the store. Returns
        validation errors (entry not stored when invalid)."""
        entry = (doc_or_entry if isinstance(doc_or_entry, GlobalContextEntry)
                 else GlobalContextEntry.from_dict(doc_or_entry))
        errs = entry.validate()
        if errs:
            return errs
        if entry.kubernetes_resource is not None:
            if self.snapshot is None:
                return ["kubernetesResource entries require a cluster snapshot"]
            self.set(entry.name, KubernetesResourceEntry(
                entry.kubernetes_resource, self.snapshot))
        else:
            if self.api_executor is None:
                return ["apiCall entries require an API executor"]
            self.set(entry.name, ExternalApiEntry(
                entry.api_call, self.api_executor))
        return []

    def refresh_all(self) -> None:
        """Poll tick for external-API entries (the controller's
        background loop)."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if isinstance(e, ExternalApiEntry):
                e.refresh()

    # -- mapping protocol for DataSources.global_context

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __getitem__(self, name: str) -> Any:
        entry = self.get_entry(name)
        if entry is None:
            raise KeyError(name)
        return entry.get()
