"""GlobalContextEntry CRD model
(api/kyverno/v2alpha1/global_context_entry_types.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.duration import parse_duration


@dataclass
class KubernetesResourceSpec:
    group: str = ""
    version: str = ""
    resource: str = ""   # plural, e.g. "deployments"
    namespace: str = ""  # empty = cluster-wide

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KubernetesResourceSpec":
        return cls(group=d.get("group", ""), version=d.get("version", ""),
                   resource=d.get("resource", ""),
                   namespace=d.get("namespace", ""))


@dataclass
class ExternalAPICallSpec:
    """kyvernov1.APICall + refreshInterval
    (global_context_entry_types.go:135)."""

    url_path: str = ""
    method: str = "GET"
    data: Optional[List[Dict[str, Any]]] = None
    service: Optional[Dict[str, Any]] = None
    jmes_path: str = ""
    refresh_interval_s: float = 600.0  # default 10m

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExternalAPICallSpec":
        interval = d.get("refreshInterval") or "10m"
        ns = parse_duration(str(interval))
        return cls(
            url_path=d.get("urlPath", ""),
            method=d.get("method", "GET"),
            data=d.get("data"),
            service=d.get("service"),
            jmes_path=d.get("jmesPath", ""),
            refresh_interval_s=(ns / 1e9) if ns else 600.0,
        )


@dataclass
class GlobalContextEntry:
    name: str
    kubernetes_resource: Optional[KubernetesResourceSpec] = None
    api_call: Optional[ExternalAPICallSpec] = None
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GlobalContextEntry":
        spec = d.get("spec") or {}
        kres = spec.get("kubernetesResource")
        call = spec.get("apiCall")
        return cls(
            name=(d.get("metadata") or {}).get("name", ""),
            kubernetes_resource=KubernetesResourceSpec.from_dict(kres) if kres else None,
            api_call=ExternalAPICallSpec.from_dict(call) if call else None,
            raw=d,
        )

    def validate(self) -> List[str]:
        """global_context_entry_types.go Validate: exactly one source,
        with its required fields."""
        errs: List[str] = []
        if self.kubernetes_resource is None and self.api_call is None:
            errs.append("a global context entry requires exactly one of "
                        "kubernetesResource or apiCall")
        if self.kubernetes_resource is not None and self.api_call is not None:
            errs.append("a global context entry cannot have both "
                        "kubernetesResource and apiCall")
        k = self.kubernetes_resource
        if k is not None:
            if not k.version:
                errs.append("kubernetesResource requires a version")
            if not k.resource:
                errs.append("kubernetesResource requires a resource")
        a = self.api_call
        if a is not None:
            if not a.url_path and not (a.service or {}).get("url"):
                errs.append("apiCall requires a urlPath or service.url")
            if a.refresh_interval_s <= 0:
                errs.append("apiCall requires a refreshInterval greater "
                            "than 0 seconds")
        return errs
