"""Image verification subsystem (host plane).

Reference parity: pkg/engine/internal/imageverifier.go (flow),
pkg/utils/image (parsing), pkg/utils/api/image.go (extraction),
pkg/imageverifycache (cache). Crypto backends are pluggable behind
``registry.StaticRegistry``'s protocol."""

from .cache import ImageVerifyCache, disabled_cache
from .extract import REGISTERED, extract_images
from .infos import BadImageError, ImageInfo, get_image_info
from .registry import (
    RegistryError,
    Response,
    StaticRegistry,
    VerificationFailed,
    VerifyOptions,
)
from .verify import (
    VERIFY_ANNOTATION,
    ImageVerificationMetadata,
    Verifier,
    expand_static_keys,
    has_verify_image_checks,
    validate_image,
    validate_image_rule,
)

__all__ = [
    "BadImageError", "ImageInfo", "get_image_info", "extract_images",
    "REGISTERED", "ImageVerifyCache", "disabled_cache", "StaticRegistry",
    "VerifyOptions", "Response", "RegistryError", "VerificationFailed",
    "Verifier", "ImageVerificationMetadata", "VERIFY_ANNOTATION",
    "expand_static_keys", "validate_image", "validate_image_rule",
    "has_verify_image_checks",
]
