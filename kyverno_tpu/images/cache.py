"""TTL'd image-verification result cache.

Mirrors pkg/imageverifycache/client.go: entries keyed by (policy id,
policy resourceVersion, rule name, image reference) so any policy edit
invalidates its entries; bounded size with oldest-first eviction; TTL
per entry (default 1h, client.go:13). Only successful verifications
are cached (imageverifier.go:283-295)."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

DEFAULT_TTL_S = 3600.0
DEFAULT_MAX_SIZE = 1000


class ImageVerifyCache:
    def __init__(self, enabled: bool = True, ttl_s: float = DEFAULT_TTL_S,
                 max_size: int = DEFAULT_MAX_SIZE, clock=time.monotonic):
        self.enabled = enabled
        self.ttl_s = ttl_s
        self.max_size = max_size
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str, str, str], float]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(policy, rule_name: str, image: str) -> Tuple[str, str, str, str]:
        # policy identity + resourceVersion: an updated policy must not
        # reuse results from its previous spec (client.go key layout).
        # Policies loaded from files carry no resourceVersion — fall
        # back to a content fingerprint of the spec so an edited spec
        # can never reuse stale entries.
        pid = f"{getattr(policy, 'namespace', '') or ''}/{getattr(policy, 'name', '')}"
        rv = str(getattr(policy, "resource_version", "") or "")
        if not rv:
            rv = getattr(policy, "_ivcache_fingerprint", "")
            if not rv:
                import hashlib
                import json
                spec = (getattr(policy, "raw", None) or {}).get("spec", {})
                rv = hashlib.sha256(
                    json.dumps(spec, sort_keys=True, default=str).encode()
                ).hexdigest()[:16]
                try:
                    object.__setattr__(policy, "_ivcache_fingerprint", rv)
                except (AttributeError, TypeError):
                    pass
        return (pid, rv, rule_name, image)

    def get(self, policy, rule_name: str, image: str) -> bool:
        if not self.enabled:
            return False
        k = self._key(policy, rule_name, image)
        now = self._clock()
        with self._lock:
            exp = self._entries.get(k)
            if exp is not None and exp > now:
                self.hits += 1
                return True
            if exp is not None:
                del self._entries[k]
            self.misses += 1
            return False

    def set(self, policy, rule_name: str, image: str) -> bool:
        if not self.enabled:
            return False
        k = self._key(policy, rule_name, image)
        with self._lock:
            self._entries[k] = self._clock() + self.ttl_s
            self._entries.move_to_end(k)
            while len(self._entries) > self.max_size:
                self._entries.popitem(last=False)
        return True


def disabled_cache() -> ImageVerifyCache:
    return ImageVerifyCache(enabled=False)
