"""Signing-envelope cryptography for image verification.

Real ECDSA P-256/SHA-256 over the two envelope formats the reference
verifies (pkg/cosign/cosign.go):

- **simple-signing payloads**: the cosign signature payload — a JSON
  document binding the image's docker-reference and manifest digest
  (``critical``) plus optional annotations — signed directly
  (cosign.go:matchSignatures / payload verification);
- **DSSE / in-toto attestation envelopes**: a base64 in-toto Statement
  signed over the DSSE v1 pre-authentication encoding
  (cosign.go:decodeStatements, in-toto attestation verify).

Keyless verification is modeled with an offline Fulcio-style CA:
ephemeral signer certificates carry the identity in a SAN and the OIDC
issuer in the Fulcio issuer extension (OID 1.3.6.1.4.1.57264.1.1);
verification checks the signature under the certificate key, validates
the chain to the trusted roots, and matches subject/issuer
(cosign.go keyless path). All primitives come from the ``cryptography``
library — no verdict is ever decided by metadata comparison.
"""

from __future__ import annotations

import base64
import datetime
import json
from typing import Any, Dict, List, Optional, Tuple

class CryptoError(Exception):
    pass


try:
    from cryptography import x509
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    # Fulcio OIDC issuer extension
    FULCIO_ISSUER_OID = x509.ObjectIdentifier("1.3.6.1.4.1.57264.1.1")
except ImportError:  # pragma: no cover - environment-dependent
    # the container may not ship `cryptography`; importing this module
    # must still succeed (the admission plane imports the images
    # subsystem unconditionally) — actual signature work raises
    # CryptoError at use time instead
    class InvalidSignature(Exception):  # type: ignore[no-redef]
        pass

    class _MissingCrypto:
        def __getattr__(self, name):
            raise CryptoError("the 'cryptography' library is not installed")

    x509 = hashes = serialization = ec = NameOID = _MissingCrypto()  # type: ignore
    FULCIO_ISSUER_OID = None


# ---------------------------------------------------------------------------
# keys


def generate_keypair() -> Tuple[ec.EllipticCurvePrivateKey, str]:
    """(private key, public key PEM) — the cosign key-pair equivalent."""
    priv = ec.generate_private_key(ec.SECP256R1())
    pem = priv.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo).decode()
    return priv, pem


def load_public_key(pem: str):
    try:
        return serialization.load_pem_public_key(pem.encode())
    except Exception as e:  # noqa: BLE001
        raise CryptoError(f"invalid public key: {e}")


def sign_blob(priv: ec.EllipticCurvePrivateKey, data: bytes) -> bytes:
    return priv.sign(data, ec.ECDSA(hashes.SHA256()))


def verify_blob(pub_pem: str, signature: bytes, data: bytes) -> bool:
    key = load_public_key(pub_pem)
    try:
        key.verify(signature, data, ec.ECDSA(hashes.SHA256()))
        return True
    except InvalidSignature:
        return False


# ---------------------------------------------------------------------------
# simple-signing payloads (cosign signature format)


def simple_signing_payload(reference: str, digest: str,
                           annotations: Optional[Dict[str, str]] = None) -> bytes:
    doc = {
        "critical": {
            "identity": {"docker-reference": reference},
            "image": {"docker-manifest-digest": digest},
            "type": "cosign container image signature",
        },
        "optional": dict(annotations or {}),
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def parse_simple_signing(payload: bytes) -> Dict[str, Any]:
    try:
        doc = json.loads(payload)
        assert isinstance(doc, dict) and "critical" in doc
        return doc
    except Exception as e:  # noqa: BLE001
        raise CryptoError(f"malformed simple-signing payload: {e}")


# ---------------------------------------------------------------------------
# DSSE / in-toto


def pae(payload_type: str, payload: bytes) -> bytes:
    """DSSE v1 pre-authentication encoding."""
    return (b"DSSEv1 %d %s %d %s"
            % (len(payload_type), payload_type.encode(),
               len(payload), payload))


INTOTO_PAYLOAD_TYPE = "application/vnd.in-toto+json"


def make_statement(digest: str, predicate_type: str,
                   predicate: Dict[str, Any], name: str = "") -> Dict[str, Any]:
    algo, _, hexd = digest.partition(":")
    return {
        "_type": "https://in-toto.io/Statement/v0.1",
        "subject": [{"name": name, "digest": {algo or "sha256": hexd}}],
        "predicateType": predicate_type,
        "predicate": predicate,
    }


def dsse_sign(priv: ec.EllipticCurvePrivateKey,
              statement: Dict[str, Any]) -> Dict[str, Any]:
    payload = json.dumps(statement, sort_keys=True,
                         separators=(",", ":")).encode()
    sig = sign_blob(priv, pae(INTOTO_PAYLOAD_TYPE, payload))
    return {
        "payloadType": INTOTO_PAYLOAD_TYPE,
        "payload": base64.b64encode(payload).decode(),
        "signatures": [{"sig": base64.b64encode(sig).decode()}],
    }


def dsse_verify(pub_pem: str, envelope: Dict[str, Any]) -> Dict[str, Any]:
    """Verify a DSSE envelope; returns the decoded statement."""
    try:
        payload = base64.b64decode(envelope["payload"])
        sigs = [base64.b64decode(s["sig"])
                for s in envelope.get("signatures", [])]
        ptype = envelope.get("payloadType", "")
    except Exception as e:  # noqa: BLE001
        raise CryptoError(f"malformed DSSE envelope: {e}")
    data = pae(ptype, payload)
    if not any(verify_blob(pub_pem, s, data) for s in sigs):
        raise CryptoError("DSSE signature verification failed")
    try:
        return json.loads(payload)
    except Exception as e:  # noqa: BLE001
        raise CryptoError(f"DSSE payload is not a statement: {e}")


# ---------------------------------------------------------------------------
# offline Fulcio-style CA (keyless + certificate attestors)


def make_ca(common_name: str = "kyverno-tpu test CA") -> Tuple[
        ec.EllipticCurvePrivateKey, str]:
    priv = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(priv.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(priv, hashes.SHA256()))
    return priv, cert.public_bytes(serialization.Encoding.PEM).decode()


def issue_signer_cert(ca_priv: ec.EllipticCurvePrivateKey, ca_cert_pem: str,
                      subject: str, issuer_url: str = "") -> Tuple[
        ec.EllipticCurvePrivateKey, str]:
    """Ephemeral signer certificate: identity in the SAN (URI or
    email), OIDC issuer in the Fulcio extension."""
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem.encode())
    priv = ec.generate_private_key(ec.SECP256R1())
    san: x509.GeneralName
    if "://" in subject:
        san = x509.UniformResourceIdentifier(subject)
    else:
        san = x509.RFC822Name(subject)
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (x509.CertificateBuilder()
               .subject_name(x509.Name([]))
               .issuer_name(ca_cert.subject)
               .public_key(priv.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(minutes=20))
               .add_extension(x509.SubjectAlternativeName([san]),
                              critical=False))
    if issuer_url:
        builder = builder.add_extension(
            x509.UnrecognizedExtension(FULCIO_ISSUER_OID, issuer_url.encode()),
            critical=False)
    cert = builder.sign(ca_priv, hashes.SHA256())
    return priv, cert.public_bytes(serialization.Encoding.PEM).decode()


def verify_cert_identity(cert_pem: str, roots_pem: str) -> Tuple[str, str]:
    """Validate the signer certificate against trusted roots and return
    (subject identity, OIDC issuer). Raises CryptoError on an untrusted
    or expired certificate."""
    try:
        cert = x509.load_pem_x509_certificate(cert_pem.encode())
    except Exception as e:  # noqa: BLE001
        raise CryptoError(f"invalid signer certificate: {e}")
    roots = []
    for block in roots_pem.split("-----END CERTIFICATE-----"):
        block = block.strip()
        if block:
            roots.append(x509.load_pem_x509_certificate(
                (block + "\n-----END CERTIFICATE-----\n").encode()))
    for root in roots:
        try:
            cert.verify_directly_issued_by(root)
            break
        except Exception:  # noqa: BLE001
            continue
    else:
        raise CryptoError("signer certificate does not chain to a trusted root")
    now = datetime.datetime.now(datetime.timezone.utc)
    if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
        raise CryptoError("signer certificate expired or not yet valid")
    subject = ""
    try:
        san = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        vals = san.get_values_for_type(x509.UniformResourceIdentifier) \
            + san.get_values_for_type(x509.RFC822Name)
        subject = vals[0] if vals else ""
    except x509.ExtensionNotFound:
        pass
    issuer = ""
    try:
        ext = cert.extensions.get_extension_for_oid(FULCIO_ISSUER_OID).value
        issuer = bytes(ext.value).decode()
    except x509.ExtensionNotFound:
        pass
    return subject, issuer


def cert_public_pem(cert_pem: str) -> str:
    cert = x509.load_pem_x509_certificate(cert_pem.encode())
    return cert.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo).decode()
