"""Image extraction from resources.

Mirrors pkg/utils/api/image.go: per-kind registered extractors (the
standard pod-spec paths for Pod and the seven pod controllers,
image.go:135 BuildStandardExtractors) overridable by a rule's
``imageExtractors`` config (kind -> [{path, value, key, name,
jmesPath}], image.go:146 lookupImageExtractor). Extraction yields
{extractor_name: {key: ImageInfo}} with JSON pointers into the
resource for digest patching.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .infos import BadImageError, ImageInfo, get_image_info


class Extractor:
    def __init__(self, fields: List[str], key: str = "", value: str = "image",
                 name: str = "", jmespath: str = ""):
        self.fields = fields          # path segments; "*" = iterate array
        self.key = key                # sibling field naming the entry
        self.value = value            # field holding the image string
        self.name = name or "custom"
        self.jmespath = jmespath


def _standard(*prefix: str) -> List[Extractor]:
    return [
        Extractor(fields=[*prefix, tag, "*"], key="name", value="image", name=tag)
        for tag in ("initContainers", "containers", "ephemeralContainers")
    ]


# kind -> extractors (image.go registeredExtractors)
REGISTERED: Dict[str, List[Extractor]] = {
    "Pod": _standard("spec"),
    "Deployment": _standard("spec", "template", "spec"),
    "DaemonSet": _standard("spec", "template", "spec"),
    "StatefulSet": _standard("spec", "template", "spec"),
    "ReplicaSet": _standard("spec", "template", "spec"),
    "ReplicationController": _standard("spec", "template", "spec"),
    "Job": _standard("spec", "template", "spec"),
    "CronJob": _standard("spec", "jobTemplate", "spec", "template", "spec"),
}


def _custom_extractors(configs: List[Dict[str, Any]]) -> List[Extractor]:
    out = []
    for c in configs:
        fields = [f.strip() for f in (c.get("path") or "").split("/") if f.strip()]
        value = c.get("value") or ""
        if not value and fields:
            value = fields[-1]
            fields = fields[:-1]
        out.append(Extractor(fields=fields, key=c.get("key") or "",
                             value=value, name=c.get("name") or "",
                             jmespath=c.get("jmesPath") or ""))
    return out


def _walk(node: Any, fields: List[str], pointer: str, hits: List) -> None:
    if node is None:
        return
    if not fields:
        hits.append((node, pointer))
        return
    f, rest = fields[0], fields[1:]
    if f == "*":
        if isinstance(node, list):
            for i, item in enumerate(node):
                _walk(item, rest, f"{pointer}/{i}", hits)
        elif isinstance(node, dict):
            for k, item in node.items():
                _walk(item, rest, f"{pointer}/{_escape(k)}", hits)
    elif isinstance(node, dict):
        _walk(node.get(f), rest, f"{pointer}/{_escape(f)}", hits)


def _escape(seg: str) -> str:
    return seg.replace("~", "~0").replace("/", "~1")


def extract_images(
    resource: Dict[str, Any],
    configs: Optional[Dict[str, List[Dict[str, Any]]]] = None,
    default_registry: str = "docker.io",
    enable_default_registry_mutation: bool = True,
    jmes=None,
) -> Dict[str, Dict[str, ImageInfo]]:
    """ExtractImagesFromResource (image.go:183): {extractor_name:
    {entry_key: ImageInfo}}. Malformed image strings raise
    BadImageError, matching the reference's error-out behavior."""
    kind = resource.get("kind", "")
    if configs and kind in configs:
        extractors = _custom_extractors(configs[kind])
    else:
        extractors = REGISTERED.get(kind, [])
    out: Dict[str, Dict[str, ImageInfo]] = {}
    for ex in extractors:
        hits: List = []
        _walk(resource, ex.fields, "", hits)
        for idx, (entry, pointer) in enumerate(hits):
            if not isinstance(entry, dict):
                continue
            value = entry.get(ex.value)
            if not isinstance(value, str) or not value.strip():
                continue
            if ex.jmespath:
                if jmes is None:
                    from ..engine.jmespath import search as jmes_search
                    value = jmes_search(ex.jmespath, value)
                else:
                    value = jmes(ex.jmespath, value)
                if not isinstance(value, str):
                    raise BadImageError(
                        f"jmespath {ex.jmespath} must produce a string")
            # without a key field, the VALUE's JSON pointer is the
            # entry key — unique across multiple same-named (default
            # "custom") extractors even when they share a path but
            # extract different fields
            value_pointer = f"{pointer}/{_escape(ex.value)}"
            key = str(entry.get(ex.key, idx)) if ex.key else (value_pointer or str(idx))
            info = get_image_info(
                value, default_registry, enable_default_registry_mutation,
                pointer=value_pointer)
            out.setdefault(ex.name, {})[key] = info
    return out
