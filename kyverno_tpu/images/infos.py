"""Image reference parsing (registry/path/name/tag/digest).

Semantics follow the reference's pkg/utils/image/infos.go: the default
registry is prepended when the first path component is not a domain
(infos.go:98 addDefaultRegistry — a domain contains ``.`` or ``:``, is
``localhost``, or has uppercase letters), the default tag is ``latest``
when neither tag nor digest is present, and ``String()`` renders
``registry/path@digest`` when digested else ``registry/path:tag``
(infos.go:34).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_REGISTRY = "docker.io"

# distribution reference grammar, trimmed to what image strings in pod
# specs can contain: [domain/]path[:tag][@digest]
_DIGEST_RE = re.compile(r"^[A-Za-z][A-Za-z0-9]*(?:[-_+.][A-Za-z][A-Za-z0-9]*)*:[0-9a-fA-F]{32,}$")
_TAG_RE = re.compile(r"^[\w][\w.-]{0,127}$")
_PATH_COMPONENT_RE = re.compile(r"^[a-z0-9]+(?:(?:\.|_|__|-+)[a-z0-9]+)*$")
_DOMAIN_RE = re.compile(r"^(?:[a-zA-Z0-9](?:[a-zA-Z0-9-]*[a-zA-Z0-9])?)(?:\.[a-zA-Z0-9](?:[a-zA-Z0-9-]*[a-zA-Z0-9])?)*(?::[0-9]+)?$")


class BadImageError(ValueError):
    pass


@dataclass
class ImageInfo:
    registry: str = ""
    name: str = ""
    path: str = ""
    tag: str = ""
    digest: str = ""
    reference: str = ""
    reference_with_tag: str = ""
    pointer: str = ""  # JSON pointer to the image field in the resource

    def __str__(self) -> str:
        image = f"{self.registry}/{self.path}" if self.registry else self.path
        if self.digest:
            return f"{image}@{self.digest}"
        return f"{image}:{self.tag}"

    def to_dict(self) -> dict:
        # the shape AddImageInfo exposes under images.<container>.<name>
        # in the JSON context (pkg/engine/context/context.go)
        return {
            "registry": self.registry,
            "name": self.name,
            "path": self.path,
            "tag": self.tag,
            "digest": self.digest,
            "reference": self.reference,
            "referenceWithTag": self.reference_with_tag,
        }


def _has_domain(image: str) -> bool:
    i = image.find("/")
    if i == -1:
        return False
    head = image[:i]
    # infos.go:100 — a leading component is a domain when it contains
    # '.'/':' or is "localhost" or is not all-lowercase
    return ("." in head or ":" in head or head == "localhost"
            or head.lower() != head)


def get_image_info(
    image: str,
    default_registry: str = DEFAULT_REGISTRY,
    enable_default_registry_mutation: bool = True,
    pointer: str = "",
) -> ImageInfo:
    """Parse an image string; raises BadImageError on malformed refs."""
    if not image or not image.strip():
        raise BadImageError("empty image")
    full = image if _has_domain(image) else f"{default_registry}/{image}"

    rest = full
    digest = ""
    if "@" in rest:
        rest, digest = rest.rsplit("@", 1)
        if not _DIGEST_RE.match(digest):
            raise BadImageError(f"bad digest in image {image!r}")
    tag = ""
    # tag separator: last ':' after the last '/'
    slash = rest.rfind("/")
    colon = rest.rfind(":")
    if colon > slash:
        rest, tag = rest[:colon], rest[colon + 1:]
        if not _TAG_RE.match(tag):
            raise BadImageError(f"bad tag in image {image!r}")

    parts = rest.split("/")
    registry, path = parts[0], "/".join(parts[1:])
    if not path:
        raise BadImageError(f"bad image {image!r}")
    if not _DOMAIN_RE.match(registry):
        raise BadImageError(f"bad registry in image {image!r}")
    for comp in path.split("/"):
        if not _PATH_COMPONENT_RE.match(comp):
            raise BadImageError(f"bad path component {comp!r} in image {image!r}")
    name = path.rsplit("/", 1)[-1]
    if not digest and not tag:
        tag = "latest"
    # when default-registry mutation is off, a defaulted registry is not
    # recorded (infos.go:73-76)
    if full != image and not enable_default_registry_mutation:
        registry = ""
    ref_with_tag = (f"{registry}/{path}:{tag}" if registry else f"{path}:{tag}")
    info = ImageInfo(registry=registry, name=name, path=path, tag=tag,
                     digest=digest, reference_with_tag=ref_with_tag,
                     pointer=pointer)
    info.reference = str(info)
    return info
