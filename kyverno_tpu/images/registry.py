"""Signature/attestation verifier backends.

The reference fans out to cosign (pkg/cosign/cosign.go) and notary
(pkg/notary/notary.go) over the network; the verification *flow*
(attestor sets, required counts, predicate-type statement matching,
digest resolution) lives above the backend in imageverifier.go. This
module defines that backend seam plus an offline static backend:

- ``ImageVerifier`` protocol: ``verify_signature(opts)`` /
  ``fetch_attestations(opts)`` returning ``Response(digest,
  statements)`` — the same split as images.ImageVerifier in
  pkg/images/client.go;
- ``StaticRegistry``: a deterministic in-memory registry (image ->
  digest, signers, attestations) used by tests, the CLI's offline mode
  and air-gapped deployments. Real cosign/notary crypto plugs in by
  implementing the same protocol; the engine flow above is unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.wildcard import match as wildcard_match


@dataclass
class VerifyOptions:
    image: str = ""
    type: str = "Cosign"           # Cosign | Notary
    key: str = ""                  # PEM public key (static key attestor)
    cert: str = ""                 # certificate attestor
    cert_chain: str = ""
    subject: str = ""              # keyless attestor
    issuer: str = ""
    roots: str = ""
    repository: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)
    predicate_type: str = ""       # for attestation fetches


@dataclass
class Response:
    digest: str = ""
    statements: List[Dict[str, Any]] = field(default_factory=list)


class RegistryError(Exception):
    """Network/registry-layer failure — maps to a rule ERROR, not FAIL
    (imageverifier.go:397 handleRegistryErrors)."""


class VerificationFailed(Exception):
    """Signature did not verify — maps to attestor failure."""


class StaticRegistry:
    """Offline registry fixture. Content:

    images: {image_ref_without_tag_or_with: {
        "digest": "sha256:...",
        "signers": [{"key": pem or "subject"/"issuer" pair,
                     "annotations": {...}, "type": "Cosign"|"Notary"}],
        "attestations": [{"type": predicateType,
                          "predicate": {...}, "signers": [...]}],
    }}
    Lookup matches the exact reference first, then the tag-stripped
    repository path.
    """

    def __init__(self, images: Optional[Dict[str, Dict[str, Any]]] = None):
        self.images = dict(images or {})

    # -- registration helpers (test/CLI fixture building)

    def add_image(self, ref: str, digest: str) -> None:
        self.images.setdefault(ref, {})["digest"] = digest

    def sign(self, ref: str, key: str = "", subject: str = "", issuer: str = "",
             annotations: Optional[Dict[str, str]] = None, sig_type: str = "Cosign") -> None:
        entry = self.images.setdefault(ref, {})
        entry.setdefault("signers", []).append({
            "key": key, "subject": subject, "issuer": issuer,
            "annotations": annotations or {}, "type": sig_type,
        })

    def attest(self, ref: str, predicate_type: str, predicate: Dict[str, Any],
               key: str = "", subject: str = "", issuer: str = "") -> None:
        entry = self.images.setdefault(ref, {})
        entry.setdefault("attestations", []).append({
            "type": predicate_type, "predicate": predicate,
            "signers": [{"key": key, "subject": subject, "issuer": issuer}],
        })

    # -- lookup

    def _entry(self, image: str) -> Dict[str, Any]:
        if image in self.images:
            return self.images[image]
        base = image.split("@", 1)[0]
        if base in self.images:
            return self.images[base]
        repo = base.rsplit(":", 1)[0] if ":" in base.rsplit("/", 1)[-1] else base
        if repo in self.images:
            return self.images[repo]
        raise RegistryError(f"image not found in registry: {image}")

    @staticmethod
    def _signer_matches(signer: Dict[str, Any], opts: VerifyOptions) -> bool:
        if opts.key:
            if signer.get("key", "").strip() != opts.key.strip():
                return False
        if opts.subject:
            if not wildcard_match(opts.subject, signer.get("subject", "")):
                return False
        if opts.issuer:
            if signer.get("issuer", "") != opts.issuer:
                return False
        for k, v in (opts.annotations or {}).items():
            if signer.get("annotations", {}).get(k) != v:
                return False
        return True

    # -- ImageVerifier protocol

    def fetch_digest(self, image: str) -> str:
        """Digest-only resolution (mutateDigest on unverified images,
        imageverifier.go:300 handleMutateDigest -> fetchImageDigest)."""
        return self._entry(image).get("digest", "")

    def verify_signature(self, opts: VerifyOptions) -> Response:
        entry = self._entry(opts.image)
        digest = entry.get("digest", "")
        for signer in entry.get("signers", []):
            if signer.get("type", "Cosign") != opts.type:
                continue
            if self._signer_matches(signer, opts):
                return Response(digest=digest)
        raise VerificationFailed(
            f"no matching signature for image {opts.image}")

    def fetch_attestations(self, opts: VerifyOptions) -> Response:
        entry = self._entry(opts.image)
        digest = entry.get("digest", "")
        statements = []
        for att in entry.get("attestations", []):
            signers = att.get("signers", [{}])
            if (opts.key or opts.subject or opts.issuer) and not any(
                    self._signer_matches(s, opts) for s in signers):
                continue
            statements.append({"type": att.get("type", ""),
                               "predicate": att.get("predicate", {})})
        if not statements and not entry.get("attestations"):
            raise VerificationFailed(
                f"no attestations found for image {opts.image}")
        return Response(digest=digest, statements=statements)
