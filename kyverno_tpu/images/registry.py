"""Signature/attestation verifier backends.

The reference fans out to cosign (pkg/cosign/cosign.go) and notary
(pkg/notary/notary.go) over the network; the verification *flow*
(attestor sets, required counts, predicate-type statement matching,
digest resolution) lives above the backend in imageverifier.go. This
module defines that backend seam plus an offline static backend:

- ``ImageVerifier`` protocol: ``verify_signature(opts)`` /
  ``fetch_attestations(opts)`` returning ``Response(digest,
  statements)`` — the same split as images.ImageVerifier in
  pkg/images/client.go;
- ``StaticRegistry``: an in-memory registry whose stored artifacts are
  REAL signing envelopes (ECDSA simple-signing payloads and DSSE
  attestations, see crypto.py) verified cryptographically — used by
  tests, the CLI's offline mode and air-gapped deployments. A
  networked cosign/notary backend plugs in behind the same protocol.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.wildcard import match as wildcard_match
from . import crypto


@dataclass
class VerifyOptions:
    image: str = ""
    type: str = "Cosign"           # Cosign | Notary
    key: str = ""                  # PEM public key (static key attestor)
    cert: str = ""                 # certificate attestor
    cert_chain: str = ""
    subject: str = ""              # keyless attestor
    issuer: str = ""
    roots: str = ""
    repository: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)
    predicate_type: str = ""       # for attestation fetches


@dataclass
class Response:
    digest: str = ""
    statements: List[Dict[str, Any]] = field(default_factory=list)


class RegistryError(Exception):
    """Network/registry-layer failure — maps to a rule ERROR, not FAIL
    (imageverifier.go:397 handleRegistryErrors)."""


class VerificationFailed(Exception):
    """Signature did not verify — maps to attestor failure."""


class StaticRegistry:
    """Offline registry holding REAL signing envelopes. Content:

    images: {image_ref: {
        "digest": "sha256:...",
        "signatures": [{"payload": b64 simple-signing JSON,
                        "signature": b64 ECDSA-P256/SHA256 sig,
                        "cert": signer cert PEM (keyless) or "",
                        "type": "Cosign"|"Notary"}],
        "attestations": [{"envelope": DSSE envelope dict,
                          "cert": signer cert PEM or ""}],
    }}
    Verification is cryptographic: signatures must verify under the
    attestor's key (or a certificate chaining to trusted roots) and the
    signed payload must bind this image's digest — nothing is decided
    by metadata comparison. Lookup matches the exact reference first,
    then the tag-stripped repository path.
    """

    def __init__(self, images: Optional[Dict[str, Dict[str, Any]]] = None):
        self.images = dict(images or {})
        self._ca = None  # lazy offline Fulcio-style CA for keyless signing

    # -- registration helpers (test/CLI fixture building)

    def add_image(self, ref: str, digest: str) -> None:
        self.images.setdefault(ref, {})["digest"] = digest

    def _keyless_ca(self):
        if self._ca is None:
            self._ca = crypto.make_ca()
        return self._ca

    @property
    def ca_roots(self) -> str:
        """Trusted roots PEM for the registry's keyless CA."""
        return self._keyless_ca()[1]

    def _repo(self, ref: str) -> str:
        base = ref.split("@", 1)[0]
        return base.rsplit(":", 1)[0] if ":" in base.rsplit("/", 1)[-1] \
            else base

    def sign(self, ref: str, key=None, subject: str = "", issuer: str = "",
             annotations: Optional[Dict[str, str]] = None,
             sig_type: str = "Cosign") -> None:
        """Produce a real signature over the simple-signing payload.
        ``key`` is an EC private key (keyed attestor); with
        ``subject``/``issuer`` instead, an ephemeral certificate is
        issued from the registry CA (keyless attestor)."""
        entry = self.images.setdefault(ref, {})
        payload = crypto.simple_signing_payload(
            self._repo(ref), entry.get("digest", ""), annotations)
        cert_pem = ""
        if key is None:
            ca_priv, ca_cert = self._keyless_ca()
            key, cert_pem = crypto.issue_signer_cert(
                ca_priv, ca_cert, subject or "nobody@example.com", issuer)
        sig = crypto.sign_blob(key, payload)
        entry.setdefault("signatures", []).append({
            "payload": base64.b64encode(payload).decode(),
            "signature": base64.b64encode(sig).decode(),
            "cert": cert_pem, "type": sig_type,
        })

    def attest(self, ref: str, predicate_type: str, predicate: Dict[str, Any],
               key=None, subject: str = "", issuer: str = "") -> None:
        """Produce a real DSSE/in-toto attestation envelope."""
        entry = self.images.setdefault(ref, {})
        statement = crypto.make_statement(
            entry.get("digest", ""), predicate_type, predicate,
            name=self._repo(ref))
        cert_pem = ""
        if key is None:
            ca_priv, ca_cert = self._keyless_ca()
            key, cert_pem = crypto.issue_signer_cert(
                ca_priv, ca_cert, subject or "nobody@example.com", issuer)
        entry.setdefault("attestations", []).append({
            "envelope": crypto.dsse_sign(key, statement), "cert": cert_pem,
        })

    # -- lookup

    def _entry(self, image: str) -> Dict[str, Any]:
        if image in self.images:
            return self.images[image]
        base = image.split("@", 1)[0]
        if base in self.images:
            return self.images[base]
        repo = base.rsplit(":", 1)[0] if ":" in base.rsplit("/", 1)[-1] else base
        if repo in self.images:
            return self.images[repo]
        raise RegistryError(f"image not found in registry: {image}")

    def _attestor_key(self, opts: VerifyOptions,
                      cert_pem: str) -> Optional[str]:
        """Resolve the public key PEM this attestor accepts for a given
        signature, applying certificate checks for keyless/cert
        attestors. Returns None when the attestor cannot accept the
        signature (wrong identity / untrusted chain)."""
        if opts.key:
            return opts.key
        if opts.cert:
            # certificate attestor: the signature must carry exactly
            # this certificate (and it must chain when a chain is given)
            if not cert_pem or cert_pem.strip() != opts.cert.strip():
                return None
            if opts.cert_chain:
                try:
                    crypto.verify_cert_identity(cert_pem, opts.cert_chain)
                except crypto.CryptoError:
                    return None
            return crypto.cert_public_pem(cert_pem)
        if opts.subject or opts.issuer:
            # keyless: chain to roots, then identity-match SAN/issuer
            if not cert_pem:
                return None
            roots = opts.roots or self.ca_roots
            try:
                subject, issuer = crypto.verify_cert_identity(cert_pem, roots)
            except crypto.CryptoError:
                return None
            if opts.subject and not wildcard_match(opts.subject, subject):
                return None
            if opts.issuer and issuer != opts.issuer:
                return None
            return crypto.cert_public_pem(cert_pem)
        # unconstrained attestor (attestations block without attestors):
        # signature crypto still runs — a certificate-bearing envelope
        # verifies against the trusted roots with no identity pinning;
        # a keyed envelope has nothing to verify against and is skipped
        if cert_pem:
            try:
                crypto.verify_cert_identity(cert_pem, opts.roots or self.ca_roots)
            except crypto.CryptoError:
                return None
            return crypto.cert_public_pem(cert_pem)
        return None

    # -- ImageVerifier protocol

    def fetch_digest(self, image: str) -> str:
        """Digest-only resolution (mutateDigest on unverified images,
        imageverifier.go:300 handleMutateDigest -> fetchImageDigest)."""
        return self._entry(image).get("digest", "")

    def verify_signature(self, opts: VerifyOptions) -> Response:
        """Cryptographically verify a simple-signing payload
        (cosign.go VerifySignature): the ECDSA signature must verify
        under the attestor's key, and the signed payload must bind this
        image's manifest digest and carry any required annotations."""
        entry = self._entry(opts.image)
        digest = entry.get("digest", "")
        last = "no signatures found"
        for sig in entry.get("signatures", []):
            if sig.get("type", "Cosign") != opts.type:
                continue
            pub = self._attestor_key(opts, sig.get("cert", ""))
            if pub is None:
                last = "no signature matched the attestor identity"
                continue
            payload = base64.b64decode(sig.get("payload", ""))
            raw = base64.b64decode(sig.get("signature", ""))
            try:
                if not crypto.verify_blob(pub, raw, payload):
                    last = "signature verification failed"
                    continue
                doc = crypto.parse_simple_signing(payload)
            except crypto.CryptoError as e:
                last = str(e)
                continue
            critical = doc.get("critical") or {}
            bound = (critical.get("image") or {}).get(
                "docker-manifest-digest", "")
            if bound != digest:
                last = (f"payload digest mismatch: signed {bound}, "
                        f"manifest has {digest}")
                continue
            optional = doc.get("optional") or {}
            if any(optional.get(k) != v
                   for k, v in (opts.annotations or {}).items()):
                last = "required annotations missing from signed payload"
                continue
            return Response(digest=digest)
        raise VerificationFailed(
            f"image {opts.image}: {last}")

    def fetch_attestations(self, opts: VerifyOptions) -> Response:
        """Verify DSSE envelopes and return the in-toto statements
        whose subject binds this image (cosign.go FetchAttestations)."""
        entry = self._entry(opts.image)
        digest = entry.get("digest", "")
        statements = []
        for att in entry.get("attestations", []):
            pub = self._attestor_key(opts, att.get("cert", ""))
            if pub is None:
                continue
            try:
                stmt = crypto.dsse_verify(pub, att.get("envelope") or {})
            except crypto.CryptoError:
                continue
            algo_hex = digest.partition(":")
            subjects = stmt.get("subject") or []
            if not any((s.get("digest") or {}).get(algo_hex[0] or "sha256")
                       == algo_hex[2] for s in subjects):
                continue  # statement signed for a different image
            statements.append({"type": stmt.get("predicateType", ""),
                               "predicate": stmt.get("predicate", {})})
        if not statements:
            raise VerificationFailed(
                f"no verifiable attestations for image {opts.image}")
        return Response(digest=digest, statements=statements)
