"""Image verification rule flow.

Re-implements the reference's verification orchestration
(pkg/engine/internal/imageverifier.go) above the pluggable backend in
``registry.py``:

- per-image guard chain (imageverifier.go:236-295): verify-images
  annotation tamper check, unchanged-image fast path, prior-annotation
  fast path, TTL cache lookup;
- attestor-set evaluation with required counts, nested attestors and
  static-key PEM splitting (imageverifier.go:489 verifyAttestorSet,
  :143 ExpandStaticKeys);
- attestation statement checks grouped by predicate type with
  any/all condition evaluation against the statement's predicate
  (imageverifier.go:206 EvaluateConditions, :405 verifyAttestations);
- digest mutation patches (imageverifier.go:200 makeAddDigestPatch)
  and the kyverno.io/verify-images metadata annotation.

The validate-side checks (required / verifyDigest,
handlers/validation/validate_image.go) live in ``validate_image``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..engine.conditions import evaluate_conditions
from ..engine.context import Context
from ..engine.response import RULE_TYPE_IMAGE_VERIFY, RuleResponse
from ..utils.wildcard import match as wildcard_match
from .cache import ImageVerifyCache
from .infos import ImageInfo
from .registry import (
    RegistryError,
    Response,
    StaticRegistry,
    VerificationFailed,
    VerifyOptions,
)

VERIFY_ANNOTATION = "kyverno.io/verify-images"  # api/kyverno/constants.go:13

_PEM_END = "-----END PUBLIC KEY-----"


@dataclass
class ImageVerificationMetadata:
    """image -> pass|skip|fail; serialized into the verify-images
    annotation so subsequent admissions skip re-verification."""

    data: Dict[str, str] = field(default_factory=dict)

    def add(self, image: str, status: str) -> None:
        self.data[image] = status

    def is_verified(self, image: str) -> bool:
        return self.data.get(image) == "pass"

    def merge(self, other: "ImageVerificationMetadata") -> None:
        self.data.update(other.data)

    def annotation_patch(self, resource: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """RFC6902 patch updating the verify-images annotation."""
        if not self.data:
            return None
        annotations = (resource.get("metadata") or {}).get("annotations") or {}
        existing = {}
        if VERIFY_ANNOTATION in annotations:
            try:
                existing = json.loads(annotations[VERIFY_ANNOTATION])
            except (ValueError, TypeError):
                existing = {}
        merged = {**existing, **self.data}
        # compact separators: the reference marshals with encoding/json
        # (no spaces), and conformance fixtures assert the exact string
        value = json.dumps(merged, sort_keys=True, separators=(",", ":"))
        if "metadata" not in resource:
            return {"op": "add", "path": "/metadata",
                    "value": {"annotations": {VERIFY_ANNOTATION: value}}}
        if not annotations:
            return {"op": "add", "path": "/metadata/annotations",
                    "value": {VERIFY_ANNOTATION: value}}
        op = "replace" if VERIFY_ANNOTATION in annotations else "add"
        return {"op": op,
                "path": "/metadata/annotations/" + VERIFY_ANNOTATION.replace("~", "~0").replace("/", "~1"),
                "value": value}

    @classmethod
    def parse_annotation(cls, data: str) -> "ImageVerificationMetadata":
        out = cls()
        parsed = json.loads(data)
        if not isinstance(parsed, dict):
            raise ValueError("verify-images annotation is not a map")
        for k, v in parsed.items():
            # historical form used booleans (engineapi.ParseImageMetadata)
            if isinstance(v, bool):
                out.data[k] = "pass" if v else "fail"
            else:
                out.data[k] = str(v)
        return out


def image_references(image_verify: Dict[str, Any]) -> List[str]:
    """The rule's reference patterns; the deprecated singular ``image``
    field folds in (image_verification_types.go:48)."""
    refs = image_verify.get("imageReferences") or []
    if not refs and image_verify.get("image"):
        refs = [image_verify["image"]]
    return refs


def matches_references(refs: List[str], image: str) -> bool:
    """imageverifier.go:99 matchReferences."""
    return any(wildcard_match(r, image) for r in refs)


def _required_count(attestor_set: Dict[str, Any]) -> int:
    entries = attestor_set.get("entries") or []
    count = attestor_set.get("count")
    if isinstance(count, int) and 0 < count <= len(entries):
        return count
    return len(entries)


def expand_static_keys(attestor_set: Dict[str, Any]) -> Dict[str, Any]:
    """Multi-key PEM bundles split into one attestor per key
    (imageverifier.go:143)."""
    entries = []
    for e in attestor_set.get("entries") or []:
        keys = (e.get("keys") or {}).get("publicKeys", "")
        if keys:
            parts = [p for p in keys.split(_PEM_END)[:-1]]
            if len(parts) > 1:
                for p in parts:
                    entries.append({"keys": {**e.get("keys", {}), "publicKeys": p + _PEM_END}})
                continue
        entries.append(e)
    return {"count": attestor_set.get("count"), "entries": entries}


class Verifier:
    """One rule's verifyImages evaluation against one resource."""

    def __init__(
        self,
        policy,
        rule_name: str,
        registry_client: Optional[StaticRegistry] = None,
        cache: Optional[ImageVerifyCache] = None,
        ivm: Optional[ImageVerificationMetadata] = None,
        context: Optional[Context] = None,
        old_resource: Optional[Dict[str, Any]] = None,
    ):
        self.policy = policy
        self.rule_name = rule_name
        self.registry = registry_client or StaticRegistry()
        self.cache = cache
        self.ivm = ivm if ivm is not None else ImageVerificationMetadata()
        self.ctx = context
        self.old_resource = old_resource

    # -- public entry (imageverifier.go:230 Verify)

    def verify(
        self,
        image_verify: Dict[str, Any],
        matched_images: List[ImageInfo],
        resource: Dict[str, Any],
    ) -> Tuple[List[Dict[str, Any]], List[RuleResponse]]:
        patches: List[Dict[str, Any]] = []
        responses: List[RuleResponse] = []
        for info in matched_images:
            image = str(info)

            if self._annotation_changed(resource):
                responses.append(RuleResponse.rule_fail(
                    self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"{VERIFY_ANNOTATION} annotation cannot be changed"))
                continue

            if self._image_unchanged(info, resource):
                self.ivm.add(image, "pass")
                continue

            if self._previously_verified(resource, image):
                self.ivm.add(image, "pass")
                continue

            digest = ""
            orig_digest = info.digest  # Go passes ImageInfo by value:
            # digests resolved during verification do not suppress the
            # mutate-digest patch (imageverifier.go:230,300)
            rule_resp: Optional[RuleResponse] = None
            if self.cache is not None and self.cache.get(self.policy, self.rule_name, image):
                rule_resp = RuleResponse.rule_pass(
                    self.rule_name, RULE_TYPE_IMAGE_VERIFY, "verified from cache")
                digest = info.digest
            else:
                rule_resp, digest = self._verify_image(image_verify, info)
                if rule_resp is not None and rule_resp.is_pass() and self.cache is not None:
                    self.cache.set(self.policy, self.rule_name, image)

            if image_verify.get("mutateDigest", True):
                patch, new_digest, err = self._mutate_digest(digest, info, orig_digest)
                if err:
                    responses.append(RuleResponse.rule_error(
                        self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                        f"failed to update digest: {err}"))
                elif patch is not None:
                    if rule_resp is None:
                        rule_resp = RuleResponse.rule_pass(
                            self.rule_name, RULE_TYPE_IMAGE_VERIFY, "mutated image digest")
                    patches.append(patch)
                    info.digest = new_digest
                    image = str(info)

            if rule_resp is not None:
                if image_verify.get("attestors") or image_verify.get("attestations"):
                    status = ("pass" if rule_resp.is_pass()
                              else "skip" if rule_resp.status == "skip" else "fail")
                    self.ivm.add(image, status)
                responses.append(rule_resp)
        return patches, responses

    # -- guards

    def _annotation_changed(self, resource: Dict[str, Any]) -> bool:
        """imageverifier.go:62 HasImageVerifiedAnnotationChanged: a
        request may not alter a previously recorded verification."""
        old = self.old_resource
        if not old or not resource:
            return False
        new_val = ((resource.get("metadata") or {}).get("annotations") or {}).get(VERIFY_ANNOTATION, "")
        old_val = ((old.get("metadata") or {}).get("annotations") or {}).get(VERIFY_ANNOTATION, "")
        if new_val == old_val:
            return False
        try:
            new_obj = ImageVerificationMetadata.parse_annotation(new_val)
            old_obj = ImageVerificationMetadata.parse_annotation(old_val)
        except (ValueError, TypeError):
            return True
        for img, status in old_obj.data.items():
            if img in new_obj.data and new_obj.data[img] != status:
                return True
        return False

    def _image_unchanged(self, info: ImageInfo, resource: Dict[str, Any]) -> bool:
        """UPDATE fast path: the image field did not change
        (imageverifier.go:251-257 via JSONContext.HasChanged)."""
        old = self.old_resource
        if not old:
            return False
        return (_resolve_pointer(old, info.pointer)
                == _resolve_pointer(resource, info.pointer) is not None)

    def _previously_verified(self, resource: Dict[str, Any], image: str) -> bool:
        """Prior-verification fast path. Deliberate hardening over the
        reference (imageverifier.go:122 isImageVerified trusts the NEW
        resource's annotation): we only honor entries that were already
        present on the OLD resource, so a request cannot mint its own
        "pass" on CREATE or smuggle new entries in on UPDATE."""
        old = self.old_resource
        if not old:
            return False
        new_ann = ((resource.get("metadata") or {}).get("annotations") or {}).get(VERIFY_ANNOTATION)
        old_ann = ((old.get("metadata") or {}).get("annotations") or {}).get(VERIFY_ANNOTATION)
        if not new_ann or not old_ann:
            return False
        try:
            new_ivm = ImageVerificationMetadata.parse_annotation(new_ann)
            old_ivm = ImageVerificationMetadata.parse_annotation(old_ann)
        except (ValueError, TypeError):
            return False
        return new_ivm.is_verified(image) and old_ivm.is_verified(image)

    # -- core (imageverifier.go:323 verifyImage)

    def _verify_image(self, image_verify: Dict[str, Any], info: ImageInfo
                      ) -> Tuple[Optional[RuleResponse], str]:
        attestors = image_verify.get("attestors") or []
        attestations = image_verify.get("attestations") or []
        if not attestors and not attestations:
            return None, ""
        image = str(info)
        if self.ctx is not None:
            self.ctx.add_image_infos({"image": info.to_dict()})
        # reference checks hoisted above the attestors branch so
        # attestation-only rules honor them too (the reference nests
        # these under `if len(attestors) > 0`, imageverifier.go:344 —
        # which silently ignores skipImageReferences for
        # attestation-only rules; deliberate fix here)
        refs = image_references(image_verify)
        if refs and not matches_references(refs, image):
            return RuleResponse.rule_skip(
                self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                f"skipping image reference image {image}"), ""
        if matches_references(image_verify.get("skipImageReferences") or [], image):
            self.ivm.add(image, "skip")
            return RuleResponse.rule_skip(
                self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                f"skipping image reference image {image}"), ""
        if attestors:
            resp, registry_resp = self._verify_attestors(attestors, image_verify, info)
            if not resp.is_pass():
                return resp, ""
            if not info.digest and registry_resp is not None:
                info.digest = registry_resp.digest
            if not attestations:
                return resp, registry_resp.digest if registry_resp else ""
        return self._verify_attestations(image_verify, info)

    def _verify_attestors(self, attestors, image_verify, info
                          ) -> Tuple[RuleResponse, Optional[Response]]:
        image = str(info)
        registry_resp: Optional[Response] = None
        for attestor_set in attestors:
            try:
                registry_resp = self._verify_attestor_set(attestor_set, image_verify, info)
            except RegistryError as e:
                return RuleResponse.rule_error(
                    self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"failed to verify image {image}: {e}"), None
            except VerificationFailed as e:
                return RuleResponse.rule_fail(
                    self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"failed to verify image {image}: {e}"), None
        if registry_resp is None:
            return RuleResponse.rule_error(
                self.rule_name, RULE_TYPE_IMAGE_VERIFY, "invalid response"), None
        return RuleResponse.rule_pass(
            self.rule_name, RULE_TYPE_IMAGE_VERIFY,
            f"verified image signatures for {image}"), registry_resp

    def _verify_attestor_set(self, attestor_set, image_verify, info) -> Response:
        attestor_set = expand_static_keys(attestor_set)
        required = _required_count(attestor_set)
        verified = 0
        errors: List[str] = []
        had_registry_error = False
        last: Optional[Response] = None
        for entry in attestor_set.get("entries") or []:
            try:
                if entry.get("attestor"):
                    last = self._verify_attestor_set(entry["attestor"], image_verify, info)
                else:
                    opts = self._build_opts(entry, image_verify, str(info))
                    last = self.registry.verify_signature(opts)
                verified += 1
                if verified >= required:
                    return last
            except RegistryError as e:
                # network-layer failures keep their class so the rule
                # surfaces as ERROR, not FAIL (imageverifier.go:397)
                had_registry_error = True
                errors.append(str(e))
            except VerificationFailed as e:
                errors.append(str(e))
        if verified >= required and last is not None:
            return last
        msg = (f"verification failed, verifiedCount: {verified}, "
               f"requiredCount: {required}, error: {'; '.join(errors) or 'none'}")
        if had_registry_error:
            raise RegistryError(msg)
        raise VerificationFailed(msg)

    def _verify_attestations(self, image_verify, info
                             ) -> Tuple[RuleResponse, str]:
        """imageverifier.go:405 verifyAttestations."""
        image = str(info)
        for i, attestation in enumerate(image_verify.get("attestations") or []):
            path = f".attestations[{i}]"
            att_type = attestation.get("type") or attestation.get("predicateType") or ""
            if not att_type:
                return RuleResponse.rule_fail(
                    self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"{path}: missing type"), ""
            attestor_sets = attestation.get("attestors") or [{"entries": [{}]}]
            for attestor_set in attestor_sets:
                required = _required_count(attestor_set)
                verified = 0
                errors: List[str] = []
                for entry in attestor_set.get("entries") or []:
                    opts = self._build_opts(entry, image_verify, image)
                    opts.predicate_type = att_type
                    try:
                        resp = self.registry.fetch_attestations(opts)
                    except (VerificationFailed, RegistryError) as e:
                        errors.append(str(e))
                        continue
                    if not info.digest:
                        info.digest = resp.digest
                        image = str(info)
                    err = self._check_statements(resp.statements, attestation, att_type)
                    if err is None:
                        verified += 1
                        if verified >= required:
                            break
                    else:
                        errors.append(err)
                if verified < required:
                    msg = "; ".join(errors) or "attestations verification failed"
                    return RuleResponse.rule_fail(
                        self.rule_name, RULE_TYPE_IMAGE_VERIFY,
                        f"image attestations verification failed, "
                        f"verifiedCount: {verified}, requiredCount: {required}, "
                        f"error: {msg}"), ""
        return RuleResponse.rule_pass(
            self.rule_name, RULE_TYPE_IMAGE_VERIFY,
            f"verified image attestations for {image}"), info.digest

    def _check_statements(self, statements, attestation, att_type) -> Optional[str]:
        matching = [s for s in statements if s.get("type") == att_type]
        if not matching:
            return f"predicate type {att_type} not found"
        conditions = attestation.get("conditions") or []
        if not conditions:
            return None
        for s in matching:
            predicate = s.get("predicate")
            if not isinstance(predicate, dict):
                return f"failed to extract predicate from statement: {s}"
            ctx = self.ctx if self.ctx is not None else Context()
            ctx.checkpoint()
            try:
                ctx.add_json(predicate)
                if not evaluate_conditions(ctx, conditions):
                    return "attestation checks failed"
            except Exception as e:  # substitution/condition errors
                return f"failed to evaluate attestation conditions: {e}"
            finally:
                ctx.restore()
        return None

    def _build_opts(self, entry: Dict[str, Any], image_verify: Dict[str, Any],
                    image: str) -> VerifyOptions:
        opts = VerifyOptions(
            image=image,
            type=image_verify.get("type") or "Cosign",
            repository=image_verify.get("repository") or "",
        )
        keys = entry.get("keys") or {}
        if keys:
            opts.key = keys.get("publicKeys", "")
        certs = entry.get("certificates") or {}
        if certs:
            opts.cert = certs.get("cert", "")
            opts.cert_chain = certs.get("certChain", "")
        keyless = entry.get("keyless") or {}
        if keyless:
            opts.subject = keyless.get("subject", "")
            opts.issuer = keyless.get("issuer", "")
            opts.roots = keyless.get("roots", "")
        if entry.get("annotations"):
            opts.annotations = dict(entry["annotations"])
        if entry.get("repository"):
            opts.repository = entry["repository"]
        return opts

    # -- digest mutation (imageverifier.go:300 handleMutateDigest)

    def _mutate_digest(self, digest: str, info: ImageInfo, orig_digest: str = ""
                       ) -> Tuple[Optional[Dict[str, Any]], str, Optional[str]]:
        if orig_digest:
            # image already pinned in the resource — nothing to patch
            return None, orig_digest, None
        if not digest:
            digest = info.digest  # resolved during verification
        if not digest:
            try:
                digest = self.registry.fetch_digest(str(info))
            except RegistryError as e:
                return None, "", str(e)
        if not digest:
            return None, "", f"digest not found for {info}"
        base = f"{info.registry}/{info.path}" if info.registry else info.path
        tagged = f"{base}:{info.tag}" if info.tag else base
        patch = {"op": "replace", "path": info.pointer,
                 "value": f"{tagged}@{digest}"}
        return patch, digest, None


def _resolve_pointer(doc: Any, pointer: str) -> Any:
    node = doc
    for seg in [s for s in pointer.split("/") if s != ""]:
        seg = seg.replace("~1", "/").replace("~0", "~")
        if isinstance(node, list):
            try:
                node = node[int(seg)]
            except (ValueError, IndexError):
                return None
        elif isinstance(node, dict):
            node = node.get(seg)
        else:
            return None
    return node


def validate_image_rule(rule_verify_images: List[Dict[str, Any]],
                        rule_name: str,
                        images: List[ImageInfo],
                        resource: Dict[str, Any]) -> List[RuleResponse]:
    """The validate-side verifyImages handler, one AGGREGATED response
    per rule (handlers/validation/validate_image.go:66-101): fail fast
    on the first failing image (missing digest under verifyDigest, or
    unverified under required); pass when any image passed or no image
    applied; skip when every applicable image was skipped. An image
    that does not match the rule's imageReferences aborts the whole
    rule with NO response (validate_image.go:74-77), which the CLI test
    harness reports as "excluded"."""
    annotations = (resource.get("metadata") or {}).get("annotations") or {}
    ivm = None
    if VERIFY_ANNOTATION in annotations:
        try:
            ivm = ImageVerificationMetadata.parse_annotation(
                annotations[VERIFY_ANNOTATION])
        except (ValueError, TypeError):
            ivm = None
    skipped: List[str] = []
    passed: List[str] = []
    for iv in rule_verify_images:
        refs = image_references(iv)
        verify_digest = iv.get("verifyDigest", True)
        required = iv.get("required", True)
        for info in images:
            image = str(info)
            if not matches_references(refs, image):
                return []
            if verify_digest and not info.digest:
                return [RuleResponse.rule_fail(
                    rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"missing digest for {image}")]
            # images not under `required` count as "not applied": they
            # land in neither list, so an all-unrequired rule passes
            # (validate_image.go:103 zero-value status)
            status = None
            if required:
                # IsImageVerified (engine/utils/image.go:68): absent or
                # unparsable annotation, or absent image entry => fail
                status = ivm.data.get(image, "fail") if ivm else "fail"
                if status == "fail":
                    return [RuleResponse.rule_fail(
                        rule_name, RULE_TYPE_IMAGE_VERIFY,
                        f"unverified image {image}")]
            if status == "skip":
                skipped.append(image)
            elif status == "pass":
                passed.append(image)
    from ..engine.response import RULE_TYPE_VALIDATION

    if passed or not (passed or skipped):
        msg = "image verified"
        if skipped:
            msg += ", skipped images: " + " ".join(skipped)
        return [RuleResponse.rule_pass(rule_name, RULE_TYPE_VALIDATION, msg)]
    return [RuleResponse.rule_skip(
        rule_name, RULE_TYPE_VALIDATION,
        "image skipped, skipped images: " + " ".join(skipped))]


def has_verify_image_checks(rule_verify_images: List[Dict[str, Any]]) -> bool:
    """rule_types.go:139 HasVerifyImageChecks: any entry with
    verifyDigest or required (both default true)."""
    return any(iv.get("verifyDigest", True) or iv.get("required", True)
               for iv in rule_verify_images or [])


def validate_image(rule_verify_images: List[Dict[str, Any]],
                   rule_name: str,
                   images: List[ImageInfo],
                   resource: Dict[str, Any]) -> List[RuleResponse]:
    """The validate-side checks (handlers/validation/validate_image.go):
    verifyDigest requires a digest on matched images; required expects
    the image recorded as verified in the annotation."""
    out: List[RuleResponse] = []
    annotations = (resource.get("metadata") or {}).get("annotations") or {}
    ivm = None
    if VERIFY_ANNOTATION in annotations:
        try:
            ivm = ImageVerificationMetadata.parse_annotation(annotations[VERIFY_ANNOTATION])
        except (ValueError, TypeError):
            ivm = None
    for iv in rule_verify_images:
        refs = image_references(iv)
        for info in images:
            image = str(info)
            if refs and not matches_references(refs, image):
                continue
            if iv.get("verifyDigest", True) and not info.digest:
                out.append(RuleResponse.rule_fail(
                    rule_name, RULE_TYPE_IMAGE_VERIFY,
                    f"missing digest for {image}"))
                continue
            if iv.get("required", True):
                if ivm is None or not ivm.is_verified(image):
                    out.append(RuleResponse.rule_fail(
                        rule_name, RULE_TYPE_IMAGE_VERIFY,
                        f"image {image} is not verified"))
                    continue
            out.append(RuleResponse.rule_pass(
                rule_name, RULE_TYPE_IMAGE_VERIFY, f"image {image} verified"))
    return out
