"""Policy-set lifecycle: versioned snapshots, compile-ahead hot swap,
per-policy quarantine, rollback under load.

The compiled policy program becomes an immutable, versioned artifact
with a controlled promotion path (snapshot -> compile-ahead -> atomic
swap) and a controlled failure path (quarantine -> rollback -> capped
retry), completing the degradation ladder started by resilience/:
serving never stalls on a recompile and never evaluates a torn set.
"""

from .manager import (PolicySetLifecycleManager, PolicySetUnavailable,
                      PolicySetVersion, QuarantineEntry, default_compile_fn)
from .snapshot import (PolicySetSnapshot, combined_hash, policy_content_hash,
                       policy_key)
from .watch import PolicyDirWatcher

__all__ = [
    "PolicyDirWatcher",
    "PolicySetLifecycleManager",
    "PolicySetSnapshot",
    "PolicySetUnavailable",
    "PolicySetVersion",
    "QuarantineEntry",
    "combined_hash",
    "default_compile_fn",
    "policy_content_hash",
    "policy_key",
]
