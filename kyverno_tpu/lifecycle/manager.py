"""Policy-set lifecycle manager — compile-ahead, atomic hot swap,
per-policy quarantine, rollback under load.

The compiled policy program is a versioned, immutable artifact:

- every PolicyCache mutation produces a PolicySetSnapshot (revision +
  content hash), and wakes the background compile worker;
- the worker lowers the new snapshot OFF the request path while every
  serving surface keeps evaluating against the last-known-good
  compiled version (acquire() never blocks on a recompile once a
  version exists);
- on success the new version is swapped in atomically — a reference
  assignment under a lock; in-flight batches finish on the version
  they pinned at flush (serving/batcher.py version_provider);
- on failure the offending policy is bisected out and QUARANTINED
  (its rules become host-fallback entries: the scalar oracle answers
  for it, per-rule ERROR when even the oracle cannot), the rest of the
  set recompiles and still runs on the device, and serving rolls back
  to (i.e. simply stays on) the prior compiled version;
- quarantined policies re-probe automatically: immediately when their
  content changes (the operator fixed the policy), and on a capped
  jittered backoff schedule otherwise (resilience/retry.py), so a
  transient compile-infrastructure failure heals without operator
  action. Set-level failures with no single culprit (every probe
  fails) count against a compile breaker instead of quarantining the
  whole set.

Chaos: the full-set compile and each bisect probe pass through the
``policyset.compile`` fault site (resilience/faults.py).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability.metrics import MetricsRegistry, global_registry
from ..observability.tracing import global_tracer
from ..resilience.breaker import CircuitBreaker
from ..resilience.faults import SITE_POLICYSET_COMPILE, global_faults
from ..resilience.retry import RetryPolicy
from .snapshot import PolicySetSnapshot, policy_key


def _oplog(event: str, level: str = "info", **fields) -> None:
    """Structured operational log (observability/log.py) — lifecycle
    transitions are exactly the events an operator greps for during an
    incident; emission must never affect the swap ladder."""
    try:
        from ..observability.log import global_oplog

        global_oplog.emit(event, level=level, **fields)
    except Exception:
        pass


class PolicySetUnavailable(RuntimeError):
    """No compiled policy-set version exists (initial compile failed
    and nothing was ever promoted). Serving layers degrade to the pure
    scalar path or resolve per failurePolicy."""


@dataclass
class QuarantineEntry:
    key: str
    error: str
    policy_hash: str       # content hash at quarantine time (heal detection)
    attempts: int = 1
    since: float = field(default_factory=time.monotonic)
    next_retry_at: float = 0.0

    def to_dict(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now is None else now
        return {
            "policy": self.key,
            "error": self.error,
            "attempts": self.attempts,
            "quarantined_for_s": round(now - self.since, 3),
            "next_retry_in_s": round(max(0.0, self.next_retry_at - now), 3),
        }


@dataclass
class PolicySetVersion:
    """One immutable compiled artifact: the snapshot it was compiled
    from, the engine serving it, and the quarantine set baked into it.
    Callers hold a reference for as long as they need it (batch
    pinning) — a swap never mutates a version in place."""

    snapshot: PolicySetSnapshot
    engine: Any  # TpuEngine (duck-typed: .cps, .scan, .coverage)
    quarantined: Tuple[str, ...] = ()
    compiled_at: float = field(default_factory=time.monotonic)

    @property
    def revision(self) -> int:
        return self.snapshot.revision

    @property
    def policies(self) -> Tuple[Any, ...]:
        return self.snapshot.policies


# compile_fn(policies, quarantine_idx) -> engine
CompileFn = Callable[[List[Any], Dict[int, str]], Any]


def default_compile_fn(exceptions=(), encode_cfg=None, meta_cfg=None,
                       data_sources=None, warm: bool = False) -> CompileFn:
    """Build a TpuEngine from a policy list with quarantined indices
    excluded from lowering. ``warm`` additionally runs one empty scan
    so the XLA program at the smallest shape bucket is built INSIDE the
    compile-ahead worker, not on the first post-swap flush."""

    def fn(policies: List[Any], quarantine: Dict[int, str]):
        from ..tpu.compiler import compile_policy_set
        from ..tpu.engine import TpuEngine

        cps = compile_policy_set(policies, encode_cfg=encode_cfg,
                                 meta_cfg=meta_cfg,
                                 data_sources=data_sources,
                                 quarantine=quarantine)
        eng = TpuEngine(cps=cps, exceptions=exceptions,
                        data_sources=data_sources)
        if warm and cps.device_programs:
            eng.scan([{}])  # pays the MIN_BUCKET jit ahead of traffic
        return eng

    return fn


class PolicySetLifecycleManager:
    """Versioned snapshots in, one atomically-swappable compiled
    version out. With the worker running, acquire() is wait-free once
    a first version exists; without it (CLI apply, unit tests), stale
    revisions compile synchronously so behavior matches the classic
    compile-on-demand path."""

    def __init__(
        self,
        cache,  # PolicyCache (duck-typed: policyset_snapshot/subscribe/revision)
        compile_fn: Optional[CompileFn] = None,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        metrics: Optional[MetricsRegistry] = None,
        warm: bool = False,
    ) -> None:
        self.cache = cache
        self._compile_fn = compile_fn or default_compile_fn(warm=warm)
        # backoff tuning for quarantine re-probes and set-level retries:
        # capped delay, so recovery is automatic and bounded
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=1, base_delay_s=0.5, max_delay_s=30.0,
            deadline_s=None)
        self.breaker = breaker or CircuitBreaker(
            name="policyset-compile", failure_threshold=3,
            reset_timeout_s=5.0, metrics=metrics)
        self.metrics = metrics or global_registry
        self._lock = threading.Lock()           # state (_active, quarantine)
        self._compile_lock = threading.Lock()   # one compile at a time
        self._active: Optional[PolicySetVersion] = None
        self._quarantine: Dict[str, QuarantineEntry] = {}  # guarded-by: _lock
        self._synced_revision = -1  # guarded-by: _compile_lock  (cache revision last reconciled)
        self._set_attempts = 0      # guarded-by: _lock  (consecutive set-level failures)
        self._set_next_retry_at = 0.0  # guarded-by: _lock
        self._failed_hash: Optional[str] = None
        self._last_error: Optional[str] = None
        self.stats: Dict[str, Any] = {
            "compiles": 0, "swaps": 0, "compile_failures": 0,
            "rollbacks": 0, "quarantine_enters": 0, "quarantine_exits": 0,
        }
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # compile-ahead lint (analysis/): after a successful swap the
        # worker runs static analysis on the ACTIVE engine — no
        # recompile, no XLA warm beyond the tile shape buckets — and
        # publishes anomalies via the OpLog / kyverno_analysis_*
        # metrics / /debug/analysis. Probing-style priority: the lint
        # runs strictly AFTER reconcile returns (the swap is already
        # atomic and served) and aborts between tiles the moment a new
        # mutation wakes the worker, so a large set's analysis never
        # delays the next swap either.
        self.analyze_on_swap = False
        self.lint_tile = 128
        self._linted_key: Optional[Tuple[str, Tuple[str, ...]]] = None
        # True while _bisect single-policy probe compiles run (always
        # under _compile_lock): compile_fns use it to skip work that
        # only the version being promoted needs (e.g. XLA warm-up)
        self._probing = False
        cache.subscribe(self._on_cache_change)

    @property
    def probing(self) -> bool:
        return self._probing

    # -- cache subscription / worker plumbing

    def _on_cache_change(self, key: str, change: str, revision: int) -> None:
        self._wake.set()

    @property
    def worker_running(self) -> bool:
        w = self._worker
        return w is not None and w.is_alive()

    def start(self) -> None:
        """Start the compile-ahead worker (idempotent)."""
        if self.worker_running:
            return
        # the worker's XLA warm scans write through the persistent
        # compile cache when one is configured (serve --xla-cache-dir /
        # KYVERNO_TPU_XLA_CACHE_DIR): a process restart then re-warms
        # from disk in seconds instead of re-paying the full build
        import os as _os

        if _os.environ.get("KYVERNO_TPU_XLA_CACHE_DIR"):
            try:
                from ..tpu.cache import enable_xla_compile_cache

                enable_xla_compile_cache()
            except Exception:
                pass  # persistence is an optimization, never a gate
        self._stopped.clear()
        self._wake.set()  # reconcile once immediately (initial compile)
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="policyset-compile-ahead")
        self._worker.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stopped.set()
        self._wake.set()
        w = self._worker
        if w is not None:
            w.join(timeout=timeout)
        self._worker = None

    def _next_wake_timeout(self) -> Optional[float]:
        """Sleep until the earliest pending retry (quarantine re-probe
        or set-level backoff); None = sleep until a mutation wakes us."""
        now = time.monotonic()
        deadlines: List[float] = []
        with self._lock:
            if self._set_next_retry_at:
                deadlines.append(self._set_next_retry_at)
            deadlines.extend(q.next_retry_at for q in self._quarantine.values())
        if not deadlines:
            return None
        return max(0.05, min(deadlines) - now)

    def _run(self) -> None:
        while not self._stopped.is_set():
            self._wake.wait(self._next_wake_timeout())
            if self._stopped.is_set():
                return
            self._wake.clear()
            try:
                self.reconcile()
            except Exception:
                # reconcile records its own failures; the worker thread
                # must survive anything (a dead worker = silent staleness)
                pass
            if self.analyze_on_swap:
                try:
                    self.run_lint()
                except Exception:
                    pass  # the lint is advisory; it must never kill
                    # the compile-ahead worker

    # -- serving-side acquisition

    def acquire(self) -> PolicySetVersion:
        """The version serving paths evaluate against. Wait-free with
        the worker running (last-known-good, compile-ahead catches up);
        synchronous compile-on-demand otherwise. Raises
        PolicySetUnavailable when no version was ever promoted."""
        v = self._active
        if self.worker_running:
            if v is None:
                v = self.reconcile()  # startup race: first compile
        else:
            rev = self.cache.revision
            if v is None or self._synced_revision != rev or self._retry_due():
                v = self.reconcile()
        if v is None:
            raise PolicySetUnavailable(
                f"no compiled policy set (last error: {self._last_error})")
        return v

    @property
    def active(self) -> Optional[PolicySetVersion]:
        return self._active

    def _retry_due(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._set_next_retry_at and now >= self._set_next_retry_at:
                return True
            return any(now >= q.next_retry_at
                       for q in self._quarantine.values())

    # -- the reconcile step (compile-ahead body)

    def reconcile(self) -> Optional[PolicySetVersion]:
        """Bring the compiled version up to date with the cache. Safe
        to call from any thread; one compile runs at a time and late
        arrivals see the result without recompiling."""
        with self._compile_lock:
            return self._reconcile_locked()

    def _reconcile_locked(self) -> Optional[PolicySetVersion]:
        now = time.monotonic()
        snap = self.cache.policyset_snapshot()
        active = self._active
        keys = snap.keys()
        key_set = set(keys)
        # quarantine bookkeeping vs the new snapshot: deleted policies
        # leave quarantine; content changes or a due retry schedule a
        # re-probe (the policy is simply NOT excluded from this compile)
        probe: set = set()
        with self._lock:
            for key in list(self._quarantine):
                q = self._quarantine[key]
                if key not in key_set:
                    del self._quarantine[key]
                    self.stats["quarantine_exits"] += 1
                elif (snap.policy_hashes.get(key) != q.policy_hash
                        or now >= q.next_retry_at):
                    probe.add(key)
            held = {k: self._quarantine[k].error
                    for k in self._quarantine if k not in probe}
        content_stale = (active is None
                         or active.snapshot.content_hash != snap.content_hash)
        quarantine_stale = (active is not None
                            and set(active.quarantined) != set(held))
        if not content_stale and not probe and not quarantine_stale:
            self._synced_revision = snap.revision
            # the cache healed BACK to the active content without a
            # compile (e.g. the offending mutation was reverted): the
            # recorded set-level failure is moot — clearing it here
            # stops the retry schedule from busy-waking the worker and
            # from reporting a stale compile error forever
            with self._lock:
                if self._failed_hash is not None:
                    self._failed_hash = None
                    self._set_attempts = 0
                    self._set_next_retry_at = 0.0
                    self._last_error = None
            return active
        # a compile already failed at this exact content: pace retries
        # with the backoff schedule instead of recompiling per acquire
        if (self._failed_hash == snap.content_hash and not probe
                and now < self._set_next_retry_at):
            return active
        if not self.breaker.allow():
            # breaker OPEN: compile infrastructure is sick; stay on the
            # last-known-good version without burning another attempt
            global_tracer.add_event("policyset_compile_deferred",
                                    breaker=self.breaker.state,
                                    revision=snap.revision)
            return active
        return self._compile_and_swap(snap, held, now, probe)

    def _try_compile(self, policies: List[Any], quarantine: Dict[int, str]):
        global_faults.fire(SITE_POLICYSET_COMPILE)
        return self._compile_fn(policies, quarantine)

    def _compile_and_swap(self, snap: PolicySetSnapshot,
                          held: Dict[str, str], now: float,
                          probe_keys: Optional[set] = None
                          ) -> Optional[PolicySetVersion]:
        keys = snap.keys()
        idx_of = {k: i for i, k in enumerate(keys)}
        q_idx = {idx_of[k]: err for k, err in held.items() if k in idx_of}
        policies = list(snap.policies)
        self.stats["compiles"] += 1
        t0 = time.monotonic()
        try:
            with global_tracer.span("policyset.compile_ahead",
                                    revision=snap.revision,
                                    policies=len(policies),
                                    quarantined=len(q_idx)):
                engine = self._try_compile(policies, q_idx)
        except Exception as e:
            offenders = self._bisect(snap, held, e, probe_keys)
            if offenders is None:
                return self._set_failure(snap, e, now)
            with self._lock:
                for key, err in offenders.items():
                    prior = self._quarantine.get(key)
                    attempts = (prior.attempts + 1) if prior else 1
                    entry = QuarantineEntry(
                        key=key, error=err,
                        policy_hash=snap.policy_hashes.get(key, ""),
                        attempts=attempts)
                    entry.next_retry_at = now + self.retry_policy.delay(
                        min(attempts - 1, 8), _rng())
                    if prior is not None:
                        entry.since = prior.since
                    self._quarantine[key] = entry
                    if prior is None:
                        self.stats["quarantine_enters"] += 1
                    global_tracer.add_event(
                        "policyset_quarantine", policy=key, error=err[:200],
                        attempts=attempts)
                    _oplog("policy_quarantined", level="warn", policy=key,
                           error=err[:200], attempts=attempts,
                           revision=snap.revision)
                held_all = {k: q.error for k, q in self._quarantine.items()}
            self._publish_quarantine()
            q_idx = {idx_of[k]: err for k, err in held_all.items()
                     if k in idx_of}
            try:
                with global_tracer.span("policyset.compile_ahead",
                                        revision=snap.revision,
                                        policies=len(policies),
                                        quarantined=len(q_idx),
                                        after_quarantine=True):
                    engine = self._try_compile(policies, q_idx)
            except Exception as e2:
                return self._set_failure(snap, e2, now)
        return self._swap_locked(snap, engine, now,
                                 compile_s=time.monotonic() - t0)

    def _bisect(self, snap: PolicySetSnapshot, held: Dict[str, str],
                err: Exception,
                probe_keys: Optional[set] = None) -> Optional[Dict[str, str]]:
        """Compile policies alone to find the culprit(s). Policies whose
        content moved since the last GOOD snapshot — plus quarantined
        policies being RE-probed this cycle (their content is unchanged
        by definition, but they are the prime suspects) — are probed
        first: the offender is almost always among them, so an N-policy
        set pays O(changed+1) probe compiles, not O(N); the full sweep
        only runs when the suspect set is clean. Returns {key: error},
        or None when the failure looks set-level/infrastructural (every
        probe failed — blaming every policy for a sick toolchain would
        quarantine the whole set)."""
        active = self._active
        baseline = active.snapshot.policy_hashes if active is not None else {}
        probe_keys = probe_keys or set()

        def probe(policies) -> Dict[str, str]:
            found: Dict[str, str] = {}
            self._probing = True
            try:
                for policy in policies:
                    try:
                        self._try_compile([policy], {})
                    except Exception as pe:
                        found[policy_key(policy)] = \
                            f"{type(pe).__name__}: {pe}"
            finally:
                self._probing = False
            return found

        eligible = [p for p in snap.policies if policy_key(p) not in held]
        changed = [p for p in eligible
                   if policy_key(p) in probe_keys
                   or baseline.get(policy_key(p))
                   != snap.policy_hashes.get(policy_key(p))]
        changed_keys = {policy_key(p) for p in changed}
        rest = [p for p in eligible if policy_key(p) not in changed_keys]
        offenders = probe(changed)
        if offenders:
            if len(offenders) < len(changed):
                # some changed policies compiled: probes demonstrably
                # work, so the failures are genuine culprits
                return offenders
            # EVERY changed policy failed — culprit or sick toolchain?
            # one unchanged sentinel probe tells them apart without
            # paying O(N) compiles
            if rest:
                return offenders if not probe([rest[0]]) else None
            return offenders if len(changed) == 1 else None
        offenders = probe(rest)
        if not offenders:
            return None  # full set failed, each policy alone compiles
        if len(rest) > 1 and len(offenders) == len(rest):
            return None  # everything failed: infrastructure, not policy
        return offenders

    def _set_failure(self, snap: PolicySetSnapshot, err: Exception,
                     now: float) -> Optional[PolicySetVersion]:
        """Set-level compile failure: keep serving the prior compiled
        version (rollback), count it on the breaker, schedule a capped
        backoff retry."""
        active = self._active
        self.breaker.record_failure()
        self.metrics.policyset_compile_failures.inc({"kind": "set"})
        with self._lock:
            self._set_attempts += 1
            self._set_next_retry_at = now + self.retry_policy.delay(
                min(self._set_attempts - 1, 8), _rng())
            self._failed_hash = snap.content_hash
            self._last_error = f"{type(err).__name__}: {err}"
            self.stats["compile_failures"] += 1
            if active is not None:
                self.stats["rollbacks"] += 1
        global_tracer.record_span(
            "policyset.rollback", now, time.monotonic(),
            target_revision=snap.revision,
            serving_revision=active.revision if active else None,
            error=self._last_error[:200], status="error")
        _oplog("policyset_rollback", level="error",
               target_revision=snap.revision,
               serving_revision=active.revision if active else None,
               error=self._last_error[:200])
        return active

    # callers hold _compile_lock (the compile-ahead path)
    def _swap_locked(self, snap: PolicySetSnapshot, engine, now: float,
              compile_s: float) -> PolicySetVersion:
        self.breaker.record_success()
        with self._lock:
            # quarantined keys NOT excluded from this engine's compiled
            # set were healed by this compile: they were in the probe
            # set, and the full-set compile including them succeeded
            excluded = _quarantined_keys(snap, engine)
            healed = [k for k in self._quarantine if k not in excluded]
            for k in healed:
                del self._quarantine[k]
                self.stats["quarantine_exits"] += 1
                global_tracer.add_event("policyset_quarantine_exit", policy=k)
                _oplog("policy_quarantine_healed", policy=k,
                       revision=snap.revision)
            quarantined = tuple(sorted(self._quarantine))
            prior = self._active
            version = PolicySetVersion(snapshot=snap, engine=engine,
                                       quarantined=quarantined)
            self._active = version   # THE swap: one reference assignment
            self._synced_revision = snap.revision
            self._set_attempts = 0
            self._set_next_retry_at = 0.0
            self._failed_hash = None
            self._last_error = None
            if prior is not None:
                self.stats["swaps"] += 1
        if prior is not None:
            self.metrics.policyset_swaps.inc()
        self.metrics.policyset_revision.set(snap.revision)
        self._publish_quarantine()
        # SLO surface: the swapped-in set's device coverage is the
        # coverage-floor SLO input (a quarantine-heavy or unloweable
        # set burning the floor shows up before latency does)
        try:
            from ..observability.analytics import global_slo

            dev, total = engine.coverage()
            global_slo.set_device_coverage(
                (dev / total) if total else 1.0)
        except Exception:
            pass
        # re-publish the DFA bank gauges for the set that is now
        # ACTIVE (probe/bisect compiles must not own these numbers)
        engine.cps.publish_dfa_gauges()
        global_tracer.record_span(
            "policyset.swap", now, time.monotonic(),
            from_revision=prior.revision if prior else None,
            to_revision=snap.revision, policies=len(snap.policies),
            quarantined=len(quarantined), compile_s=round(compile_s, 4))
        _oplog("policyset_swap",
               from_revision=prior.revision if prior else None,
               to_revision=snap.revision, policies=len(snap.policies),
               quarantined=len(quarantined), compile_s=round(compile_s, 4))
        return version

    def _publish_quarantine(self) -> None:
        with self._lock:
            n = len(self._quarantine)
        self.metrics.policyset_quarantined.set(n)

    # -- compile-ahead lint (analysis/)

    def run_lint(self, force: bool = False) -> Optional[Any]:
        """Static analysis of the ACTIVE version's already-compiled
        engine (no recompile — the engine IS the artifact the swap
        promoted; its XLA programs are already warm from serving).
        Idempotent per (content hash, quarantine set); ``force``
        re-runs regardless. Returns the AnalysisReport, or None when
        nothing is active, the version was already linted, or a
        pending policy-set change preempted the run (the worker's next
        wake retries — the linted key is only recorded on success)."""
        version = self._active
        if version is None:
            return None
        key = (version.snapshot.content_hash, version.quarantined)
        if not force and key == self._linted_key:
            return None
        from ..analysis import global_analysis, run_analysis

        global_analysis.lint_enabled = True

        def should_abort() -> bool:
            # a pending policy-set change preempts the lint: the cache
            # revision moving past the linted snapshot is the signal (a
            # raw _wake check would wedge sync-mode callers — nothing
            # clears the event without a worker)
            try:
                stale = self.cache.revision != version.snapshot.revision
            except Exception:
                stale = False
            return (self._stopped.is_set() or self._active is not version
                    or stale)

        t0 = time.monotonic()
        with global_tracer.span("policyset.lint", revision=version.revision,
                                policies=len(version.policies)):
            report = run_analysis(version.engine, tile=self.lint_tile,
                                  should_abort=should_abort)
        if report is None:
            # preempted between tiles: the mutation that aborted us
            # already set _wake, so the worker loops straight back into
            # reconcile and re-lints whatever version wins
            return None
        self._linted_key = key
        self.stats["lints"] = self.stats.get("lints", 0) + 1
        for a in report.anomalies:
            _oplog("policy_anomaly", level="warn", kind=a.kind,
                   policy=a.policy, rule=a.rule,
                   other=(f"{a.other_policy}/{a.other_rule}"
                          if a.other_policy or a.other_rule else ""),
                   detail=a.detail[:200], revision=version.revision)
        _oplog("policyset_lint", revision=version.revision,
               witnesses=report.stats.get("witnesses", 0),
               anomalies=report.counts(),
               wall_s=round(time.monotonic() - t0, 3))
        return report

    # -- introspection

    def state(self) -> Dict[str, Any]:
        """JSON-ready lifecycle snapshot for /readyz and /debug/state."""
        now = time.monotonic()
        active = self._active
        with self._lock:
            quarantined = [q.to_dict(now) for q in
                           sorted(self._quarantine.values(),
                                  key=lambda q: q.key)]
            stats = dict(self.stats)
            last_error = self._last_error
            retry_in = (max(0.0, self._set_next_retry_at - now)
                        if self._set_next_retry_at else None)
        out: Dict[str, Any] = {
            "active_revision": active.revision if active else None,
            "active_content_hash": (active.snapshot.content_hash
                                    if active else None),
            "cache_revision": self.cache.revision,
            "worker_running": self.worker_running,
            "compile_breaker": self.breaker.state,
            "quarantined": quarantined,
            "stats": stats,
        }
        if active is not None:
            dev, total = active.engine.coverage()
            out["device_rules"] = dev
            out["total_rules"] = total
            out["policies"] = [policy_key(p) for p in active.policies]
        if last_error:
            out["last_compile_error"] = last_error
        if retry_in is not None:
            out["set_retry_in_s"] = round(retry_in, 3)
        return out


def _quarantined_keys(snap: PolicySetSnapshot, engine) -> set:
    """Keys of policies the ENGINE's compiled set actually excluded."""
    keys = snap.keys()
    return {keys[i] for i in getattr(engine.cps, "quarantined", {}) or {}
            if i < len(keys)}


_rng_local = threading.local()


def _rng():
    import random

    r = getattr(_rng_local, "rng", None)
    if r is None:
        r = _rng_local.rng = random.Random()
    return r
