"""PolicySetSnapshot — the immutable input of a compile.

Every mutation of the live policy set produces a new snapshot: the
cache revision, the (autogen-expanded) policy list frozen as a tuple,
a per-policy content hash, and a combined content hash over the whole
set. The hash is what the compile-ahead worker keys its work on — two
revisions with identical content (a no-op re-apply) share one compiled
artifact, and a swapped-in version can always say exactly which bytes
it was compiled from (the DPI-engine discipline: compiled automata are
replaced atomically, never patched live).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Tuple


def policy_key(policy: Any) -> str:
    """Cache key of a policy object: ``namespace/name`` for namespaced
    Policy, bare ``name`` for ClusterPolicy (policycache.py keying)."""
    ns = getattr(policy, "namespace", "") or ""
    name = getattr(policy, "name", "") or ""
    return f"{ns}/{name}" if ns else name


def policy_content_hash(policy: Any) -> str:
    """Stable content hash of one policy. The raw parsed document is
    the canonical content (api/policy.py retains it); policies built
    programmatically without a raw dict hash their identity + spec
    repr, which is stable within a process — enough for churn
    detection, which is all this hash feeds."""
    raw = getattr(policy, "raw", None)
    if raw:
        payload = json.dumps(raw, sort_keys=True, default=str)
    else:
        payload = "|".join((
            getattr(policy, "namespace", "") or "",
            getattr(policy, "name", "") or "",
            getattr(policy, "resource_version", "") or "",
            repr(getattr(policy, "spec", None)),
        ))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def combined_hash(policy_hashes: Dict[str, str]) -> str:
    """Order-insensitive hash of the whole set: sorted (key, hash)
    pairs, so insertion order never forces a spurious recompile."""
    payload = ";".join(f"{k}={h}" for k, h in sorted(policy_hashes.items()))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


@dataclass(frozen=True)
class PolicySetSnapshot:
    """Immutable view of the policy set at one cache revision."""

    revision: int
    policies: Tuple[Any, ...]          # autogen-expanded, cache order
    policy_hashes: Dict[str, str] = field(default_factory=dict)
    content_hash: str = ""

    def __post_init__(self) -> None:
        if not self.content_hash:
            object.__setattr__(
                self, "content_hash", combined_hash(self.policy_hashes))

    def keys(self) -> Tuple[str, ...]:
        return tuple(policy_key(p) for p in self.policies)
