"""Policy directory watcher — `serve --policy-watch DIR`.

Polls a directory of policy YAML/JSON files on an interval (mtime +
size signature first, content hash on movement) and reconciles the
PolicyCache to the directory's contents: new/changed policies are
set (only when their content hash actually moved — a touch without a
content change never burns a revision), policies that disappear from
every file are unset. Each cache mutation then flows through the
lifecycle manager's compile-ahead ladder, so a `kubectl cp`-style
deploy of a policy file hot-swaps the compiled set without a restart.

A file that fails to parse is SKIPPED (its previously loaded policies
stay live): a truncated write observed mid-poll must not unload half
the policy set. The parse error is kept in state() for /debug/state.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

import yaml

from ..api.policy import ClusterPolicy, is_policy_document
from .snapshot import policy_content_hash, policy_key

_POLICY_EXTS = (".yaml", ".yml", ".json")


class PolicyDirWatcher:
    def __init__(self, path: str, cache, interval_s: float = 2.0) -> None:
        self.path = path
        self.cache = cache
        self.interval_s = interval_s
        self._sig: Dict[str, Tuple[float, int]] = {}     # guarded-by: _lock  (file -> (mtime, size))
        self._content: Dict[str, str] = {}               # guarded-by: _lock  (file -> content hash)
        self._file_keys: Dict[str, Set[str]] = {}        # guarded-by: _lock  (file -> policy keys)
        self._loaded_hash: Dict[str, str] = {}           # guarded-by: _lock  (policy key -> hash)
        self._errors: Dict[str, str] = {}                # guarded-by: _lock  (file -> parse error)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"polls": 0, "syncs": 0, "set": 0, "unset": 0,
                      "parse_errors": 0}

    # -- polling

    def _list_files(self) -> List[str]:
        out: List[str] = []
        for root, _dirs, files in os.walk(self.path):
            for f in sorted(files):
                if f.lower().endswith(_POLICY_EXTS):
                    out.append(os.path.join(root, f))
        return sorted(out)

    def _parse_file(self, path: str) -> List[ClusterPolicy]:
        with open(path, "rb") as f:
            raw = f.read()
        policies = []
        for doc in yaml.safe_load_all(raw.decode("utf-8")):
            if isinstance(doc, dict) and is_policy_document(doc):
                policies.append(ClusterPolicy.from_dict(doc))
        return policies

    def sync_once(self) -> bool:
        """One poll pass; returns True when any cache mutation landed.

        The IO half (directory walk, stat/hash of every file, YAML
        parse of changed ones) runs WITHOUT the lock against a locked
        snapshot of the signature maps — state() is served on the HTTP
        debug thread and must never stall behind a slow disk or a big
        parse. Only the apply half (ownership/ledger mutations and the
        cache set/unset calls) runs under _lock, so a scrape mid-pass
        sees either the old maps or the new ones, never a resize in
        flight. Poll passes themselves never run concurrently (one
        watcher thread; manual sync_once callers are sequential), so
        reading the snapshot and applying later cannot lose updates."""
        with self._lock:
            self.stats["polls"] += 1
            sig_snap = dict(self._sig)
            content_snap = dict(self._content)
            known_files = list(self._file_keys)
        files = self._list_files()
        present = set(files)
        # cheap signature pass first, content hash only on movement,
        # parse only on content movement — all outside the lock
        new_sigs: Dict[str, Tuple[float, int]] = {}
        new_content: Dict[str, str] = {}
        parsed: Dict[str, List[ClusterPolicy]] = {}
        parse_errors: Dict[str, str] = {}
        for path in files:
            try:
                st = os.stat(path)
                sig = (st.st_mtime, st.st_size)
            except OSError:
                continue  # raced a delete; next poll settles it
            if sig_snap.get(path) == sig:
                continue
            try:
                with open(path, "rb") as f:
                    h = hashlib.sha256(f.read()).hexdigest()
            except OSError:
                continue
            new_sigs[path] = sig
            if content_snap.get(path) != h:
                new_content[path] = h
                try:
                    parsed[path] = self._parse_file(path)
                except Exception as e:  # noqa: BLE001 — bad file, keep prior
                    parse_errors[path] = f"{type(e).__name__}: {e}"
        removed_files = [p for p in known_files if p not in present]
        if not new_sigs and not removed_files:
            return False
        with self._lock:
            return self._apply_locked(new_sigs, new_content, parsed,
                                      parse_errors, removed_files)

    def _apply_locked(self, new_sigs, new_content, parsed, parse_errors,
                      removed_files) -> bool:
        self._sig.update(new_sigs)
        self._content.update(new_content)
        if not new_content and not removed_files and not parse_errors:
            return False
        mutated = False
        # phase 1: apply every set and update EVERY file's ownership
        # before any unset decision — a policy that moved between two
        # files in the same poll must never be transiently unloaded
        # (the stale ownership map would call it unowned mid-pass)
        gone: Set[str] = set()
        for path, err in parse_errors.items():
            self._errors[path] = err
            self.stats["parse_errors"] += 1
        for path, policies in parsed.items():
            self._errors.pop(path, None)
            new_keys = set()
            for p in policies:
                key = policy_key(p)
                new_keys.add(key)
                h = policy_content_hash(p)
                if self._loaded_hash.get(key) != h:
                    self.cache.set(p)
                    self._loaded_hash[key] = h
                    self.stats["set"] += 1
                    mutated = True
            gone |= self._file_keys.get(path, set()) - new_keys
            self._file_keys[path] = new_keys
        for path in removed_files:
            gone |= self._file_keys.pop(path, set())
            self._sig.pop(path, None)
            self._content.pop(path, None)
            self._errors.pop(path, None)
        # phase 2: unload what no watched file declares anymore
        mutated |= self._unset_unowned_locked(gone)
        if mutated:
            self.stats["syncs"] += 1
        return mutated

    def _unset_unowned_locked(self, keys: Set[str]) -> bool:
        mutated = False
        for key in keys:
            if any(key in owned for owned in self._file_keys.values()):
                continue  # still declared by another file
            ns, _, name = key.rpartition("/")
            self.cache.unset(name, ns)
            self._loaded_hash.pop(key, None)
            self.stats["unset"] += 1
            mutated = True
        return mutated

    # -- thread lifecycle

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="policy-dir-watcher")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:  # the watcher must outlive any poll error
                pass
            self._stop.wait(self.interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "interval_s": self.interval_s,
                "files": len(self._sig),
                "loaded_policies": len(self._loaded_hash),
                "parse_errors": dict(self._errors),
                "stats": dict(self.stats),
            }
