"""Device-triaged batched mutation.

The mutate workload's batched front door (ISSUE 16 / ROADMAP item 3):

- ``triage.py`` — compile a mutate rule's match/exclude/preconditions
  into a *needs-mutation* predicate (a validate ``deny: {}`` shell)
  through the existing IR compiler, so triage evaluates as a device
  cross-product over encoded columnar rows. Most admissions are
  triage-negative and never touch the host patcher.
- ``lowering.py`` — lower constant add/replace strategic-merge
  overlays into precomputed ``PatchTemplate``s stamped per
  triage-positive row, bit-identical to ``engine/mutate.py`` (the
  scalar oracle), plus the read/write-path analysis that demotes
  chain-dependent rules to host triage.
- ``coordinator.py`` — per-resource application: templates where
  lowerable, the scalar patcher everywhere else, chaining the patched
  resource across policies exactly like ``Engine.mutate``.

Degradation ladder: device triage -> host patcher -> per-rule ERROR.
"""

from .lowering import (PatchTemplate, lower_mutate_rule, paths_conflict,
                       rule_read_paths, rule_write_paths)
from .triage import synthetic_triage_policy, triage_rule

__all__ = [
    "PatchTemplate",
    "lower_mutate_rule",
    "paths_conflict",
    "rule_read_paths",
    "rule_write_paths",
    "synthetic_triage_policy",
    "triage_rule",
]
