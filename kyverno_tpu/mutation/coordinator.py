"""Batched-mutation coordinator: triage rows in, patched resource out.

Per admission the webhook hands over one resource plus the (M,) triage
row list from ``TpuEngine.triage_mutate`` (bank order). The coordinator
walks policies in compiled-bank order and, per policy, takes exactly
one of three paths:

- **skip** — every rule row is triage-negative (SKIP / NOT_MATCHED):
  the policy never touches the resource. This is the ~95% case the
  device batch exists for.
- **template** — every row is decidable on device and every positive
  rule carries a lowered ``PatchTemplate``: stamp the templates in
  rule order, bit-identical to the scalar patcher.
- **scalar** — anything else (host-routed rows, positive rules outside
  the lowerable subset, or a template stamp that throws): run the full
  policy through ``Engine.mutate``, which re-evaluates predicates
  host-side and chains patches exactly like the legacy path.

Patched output chains across policies either way, so a later policy's
scalar pass sees earlier template stamps and vice versa. Scalar-path
crashes degrade to per-rule ERROR entries with the resource left as it
was before that policy — the bottom of the degradation ladder.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..resilience.faults import SITE_MUTATE_PATCH, global_faults


@dataclass
class MutationOutcome:
    """Result of one coordinated mutate pass over all policies."""

    patched: Any
    changed: bool = False
    template_rules: int = 0     # rules applied by template stamp
    scalar_policies: int = 0    # policies routed to Engine.mutate
    skipped_policies: int = 0   # all-negative policies (never touched)
    fallbacks: int = 0          # template paths that degraded to scalar
    errors: List[Tuple[str, str, str]] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)


def _scalar_policy(engine: Any, policy: Any, patched: Any,
                   namespace_labels: Optional[Dict[str, str]],
                   operation: str, admission_info: Any,
                   out: MutationOutcome) -> Any:
    """Run one policy through the scalar patcher; returns the (possibly
    new) patched resource. Crashes become per-rule ERROR entries."""
    from ..tpu.engine import build_scan_context

    try:
        pctx = build_scan_context(policy, patched, namespace_labels,
                                  operation, admission_info)
        resp = engine.scalar.mutate(pctx)
    except Exception as e:  # noqa: BLE001 — ladder bottom: per-rule ERROR
        for rule in policy.get_rules():
            if rule.has_mutate():
                out.errors.append((policy.name, rule.name,
                                   f"scalar patcher crashed: {e}"))
        return patched
    out.scalar_policies += 1
    for rr in resp.policy_response.rules:
        if rr.status == "error":
            out.errors.append((policy.name, rr.name, rr.message))
    new = resp.patched_resource
    return patched if new is None else new


def apply_mutations(
    engine: Any,
    resource: Dict[str, Any],
    rows: Sequence[Tuple[Tuple[str, str], int]],
    namespace_labels: Optional[Dict[str, str]] = None,
    operation: str = "CREATE",
    admission_info: Any = None,
    registry: Any = None,
) -> MutationOutcome:
    """Apply every mutate policy in ``engine.cps`` to ``resource``,
    routed by ``rows`` — the bank-ordered ``((policy, rule), code)``
    triage verdicts (an all-HOST list degrades everything to the scalar
    patcher, which is the pipeline's fallback/hedge contract)."""
    from ..tpu.evaluator import ERROR, HOST, NOT_MATCHED, SKIP

    if registry is None:
        from ..observability.metrics import global_registry as registry

    cps = engine.cps
    out = MutationOutcome(patched=resource)
    if not cps.mutate_rules:
        return out

    codes = {ident: int(code) for ident, code in rows}
    templates = dict(zip(cps.mutate_rules, cps.mutate_templates))
    by_policy: Dict[str, List[Tuple[str, str]]] = {}
    for ident in cps.mutate_rules:
        by_policy.setdefault(ident[0], []).append(ident)
    policies = {p.name: p for p in cps.policies}

    patched = resource
    for pname, idents in by_policy.items():
        policy = policies.get(pname)
        if policy is None:
            continue
        pcodes = [codes.get(i, HOST) for i in idents]
        if all(c in (SKIP, NOT_MATCHED) for c in pcodes):
            out.skipped_policies += 1
            continue
        host = any(c == ERROR or c >= HOST for c in pcodes)
        positive = [i for i, c in zip(idents, pcodes)
                    if c not in (SKIP, NOT_MATCHED, ERROR) and c < HOST]
        if host or any(templates.get(i) is None for i in positive):
            patched = _scalar_policy(engine, policy, patched,
                                     namespace_labels, operation,
                                     admission_info, out)
            registry.mutate_patches.inc({"source": "scalar"})
            continue
        try:
            global_faults.fire(SITE_MUTATE_PATCH)
            stamped = patched
            for ident in positive:
                stamped = templates[ident].stamp(stamped)
            patched = stamped
            out.template_rules += len(positive)
            registry.mutate_patches.inc({"source": "template"},
                                        len(positive))
        except Exception as e:  # noqa: BLE001 — degrade to the oracle
            out.fallbacks += 1
            out.warnings.append(f"{pname}: template stamp fell back "
                                f"to scalar patcher: {e}")
            registry.mutate_patch_fallbacks.inc()
            patched = _scalar_policy(engine, policy, patched,
                                     namespace_labels, operation,
                                     admission_info, out)
            registry.mutate_patches.inc({"source": "scalar"})

    out.patched = patched
    out.changed = patched is not resource and patched != resource
    return out
