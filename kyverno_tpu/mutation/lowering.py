"""Vectorized patch synthesis: constant strategic-merge overlays
lowered into precomputed templates, plus the chain-dependency analysis
that keeps device triage sound.

Lowerable subset (the dominant admission-mutation shape — default
labels/annotations, securityContext defaults): a mutate rule whose only
patch is a ``patchStrategicMerge`` overlay of plain keys and
``+(key)`` add-if-not-present anchors, with no variables (``{{``), no
condition/negation/existence/equality anchors, no context entries, and
no lists under plain keys except all-scalar replacement lists. For
this subset the merge result depends on the target resource only
through copy-on-write dict merging and absent-key adds — both
precomputable — so ``PatchTemplate.stamp`` reproduces
``engine/mutate.py``'s ``strategic_merge`` bit-identically without
walking the overlay per resource. Everything else falls through to the
scalar patcher (the bit-identity oracle).

Chain dependency: the scalar chain evaluates rule *j*'s
match/preconditions against the patched-so-far resource, while device
triage evaluates against the ORIGINAL. ``rule_write_paths`` /
``rule_read_paths`` over-approximate each rule's written and
predicate-read path sets (``None`` = unknown = everything), and the
compiler demotes rule *j* to host triage when any earlier mutate rule
may write a path *j*'s predicate reads.
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..api.policy import Rule
from ..engine import anchor as anchorpkg
from ..engine.mutate import _strip_anchors, load_json6902

Path = Tuple[str, ...]
# None = top (unknown / everything): any analysis that cannot bound a
# rule's path set returns None and the conflict check stays conservative
PathSet = Optional[List[Path]]


# ---------------------------------------------------------------------------
# patch templates


@dataclass
class PatchTemplate:
    """One lowered ``patchStrategicMerge`` overlay.

    ``entries`` is the compiled op list in overlay key order — the same
    order ``_merge_element`` walks — where each op is one of::

        ("set",   key, value)                 # plain key, constant value
        ("add",   key, payload)               # +(key), stamped if absent
        ("merge", key, sub_entries, stripped) # plain key, dict value

    ``stripped`` mirrors ``_strip_anchors(overlay)`` for the paths the
    oracle replaces wholesale (non-dict merge targets)."""

    policy_name: str
    rule_name: str
    entries: List[Tuple] = field(default_factory=list)
    stripped: Any = None
    write_paths: List[Path] = field(default_factory=list)

    def stamp(self, resource: Any) -> Any:
        """Apply the template; returns the patched copy (resource
        untouched), value-identical to
        ``strategic_merge(resource, overlay)`` for the lowered rule."""
        if not isinstance(resource, dict):
            # dict overlay on a non-dict: the oracle replaces with the
            # stripped overlay (_merge_element's first branch)
            return copy.deepcopy(self.stripped)
        return _stamp_entries(resource, self.entries)


def _stamp_entries(resource: Dict[str, Any], entries: List[Tuple]) -> Dict[str, Any]:
    out = dict(resource)  # copy-on-write, like _merge_element
    for op in entries:
        kind, key = op[0], op[1]
        if kind == "set":
            value = op[2]
            out[key] = copy.deepcopy(value) \
                if isinstance(value, (dict, list)) else value
        elif kind == "add":
            if key not in out:
                out[key] = copy.deepcopy(op[2])
        else:  # merge
            target = out.get(key)
            if isinstance(target, dict):
                out[key] = _stamp_entries(target, op[2])
            else:
                out[key] = copy.deepcopy(op[3])
    return out


def _has_variable(node: Any) -> bool:
    if isinstance(node, dict):
        return any(_has_variable(k) or _has_variable(v)
                   for k, v in node.items())
    if isinstance(node, list):
        return any(_has_variable(x) for x in node)
    return isinstance(node, str) and "{{" in node


def _has_anchor_key(node: Any) -> bool:
    if isinstance(node, dict):
        return any(anchorpkg.parse(k) is not None or _has_anchor_key(v)
                   for k, v in node.items())
    if isinstance(node, list):
        return any(_has_anchor_key(x) for x in node)
    return False


def _contains_dict(node: Any) -> bool:
    if isinstance(node, dict):
        return True
    if isinstance(node, list):
        return any(_contains_dict(x) for x in node)
    return False


def _compile_overlay(overlay: Dict[str, Any]) -> Optional[List[Tuple]]:
    """Compile one overlay map level; None = not lowerable."""
    entries: List[Tuple] = []
    for key, value in overlay.items():
        if not isinstance(key, str) or "{{" in key:
            return None
        a = anchorpkg.parse(key)
        if anchorpkg.is_add_if_not_present(a):
            # payload stamped verbatim when the key is absent; any
            # nested anchor or variable would make _strip_anchors /
            # substitution resource- or context-dependent
            if _has_variable(value) or _has_anchor_key(value):
                return None
            entries.append(("add", a.key, copy.deepcopy(value)))
            continue
        if a is not None:
            # condition/negation/existence/equality anchors gate the
            # merge on resource content — scalar patcher territory
            return None
        if isinstance(value, dict):
            sub = _compile_overlay(value)
            if sub is None:
                return None
            entries.append(("merge", key, sub, _strip_anchors(value)))
        elif isinstance(value, list):
            # _merge_list replaces for non-empty scalar lists whatever
            # the target holds; dict elements merge per-element by name
            # (target-dependent) and empty overlays no-op on lists but
            # replace non-lists — neither is constant
            if not value or _contains_dict(value) or _has_variable(value):
                return None
            entries.append(("set", key, copy.deepcopy(value)))
        elif isinstance(value, str):
            if "{{" in value:
                return None
            entries.append(("set", key, value))
        else:
            entries.append(("set", key, value))
    return entries


def lower_mutate_rule(rule: Rule) -> Optional[PatchTemplate]:
    """Lower a mutate rule into a PatchTemplate, or None when the rule
    is outside the lowerable subset (it then rides the scalar patcher
    when triage-positive). Never raises."""
    try:
        m = rule.mutation
        if not isinstance(m, dict) or rule.context:
            return None
        overlay = m.get("patchStrategicMerge")
        if overlay is None or not isinstance(overlay, dict):
            return None
        if any(v is not None for k, v in m.items()
               if k != "patchStrategicMerge"):
            return None
        entries = _compile_overlay(overlay)
        if entries is None:
            return None
        writes = _overlay_write_paths(overlay, ())
        if writes is None:
            return None
        return PatchTemplate(
            policy_name="", rule_name=rule.name, entries=entries,
            stripped=_strip_anchors(overlay), write_paths=writes)
    except Exception:  # noqa: BLE001 — lowering must never fail a compile
        return None


# ---------------------------------------------------------------------------
# write-path analysis (what a mutate rule may change)


def _overlay_write_paths(overlay: Any, prefix: Path) -> PathSet:
    if not isinstance(overlay, dict):
        return None
    out: List[Path] = []
    for key, value in overlay.items():
        if not isinstance(key, str) or "{{" in key:
            return None  # substituted key — unbounded write target
        a = anchorpkg.parse(key)
        k = a.key if a is not None else key
        if "{{" in k:
            return None
        if isinstance(value, dict) and a is None:
            sub = _overlay_write_paths(value, prefix + (k,))
            if sub is None:
                return None
            out.extend(sub)
        else:
            # anchored keys, lists, and scalars write (at most) the
            # whole subtree at this key
            out.append(prefix + (k,))
    return out


def _json6902_write_paths(patch: Any) -> PathSet:
    try:
        ops = load_json6902(patch)
    except Exception:  # noqa: BLE001
        return None
    out: List[Path] = []
    for p in ops:
        if not isinstance(p, dict) or _has_variable(p):
            return None
        if p.get("op") == "test":
            continue  # reads only
        for ptr_key in ("path",) + (("from",) if p.get("op") == "move" else ()):
            ptr = p.get(ptr_key, "")
            if not isinstance(ptr, str) or not ptr.startswith("/"):
                return None
            segs: List[str] = []
            for seg in ptr.split("/")[1:]:
                seg = seg.replace("~1", "/").replace("~0", "~")
                if seg == "-" or seg.lstrip("-").isdigit():
                    break  # index writes touch the parent list subtree
                segs.append(seg)
            out.append(tuple(segs))
    return out


def rule_write_paths(rule: Rule) -> PathSet:
    """Over-approximate path prefixes a mutate rule may write; None =
    unbounded (foreach lists with variable targets, substituted keys,
    targets, unknown patch kinds)."""
    try:
        m = rule.mutation
        if not isinstance(m, dict):
            return None
        out: List[Path] = []
        for key, body in m.items():
            if body is None:
                continue
            if key == "patchStrategicMerge":
                sub = _overlay_write_paths(body, ())
            elif key == "patchesJson6902":
                sub = _json6902_write_paths(body)
            elif key == "foreach":
                sub = []
                for fe in body if isinstance(body, list) else [None]:
                    if not isinstance(fe, dict):
                        return None
                    if fe.get("patchStrategicMerge") is not None:
                        s = _overlay_write_paths(
                            fe["patchStrategicMerge"], ())
                    elif fe.get("patchesJson6902") is not None:
                        s = _json6902_write_paths(fe["patchesJson6902"])
                    else:
                        s = None
                    if s is None:
                        return None
                    sub.extend(s)
            else:
                return None  # targets / unknown mutate construct
            if sub is None:
                return None
            out.extend(sub)
        return out
    except Exception:  # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# predicate read-path analysis (what device triage evaluates against
# the ORIGINAL resource)

_VAR_RE = re.compile(r"\{\{(.*?)\}\}", re.S)
_OBJ_PATH_RE = re.compile(
    r"^request\.object\.([A-Za-z0-9_][\w\-]*(?:\.[A-Za-z0-9_][\w\-]*)*)$")
# variables whose value does not read the admission resource at all
_RESOURCE_FREE_RE = re.compile(
    r"^(request\.operation|request\.userInfo(\.[\w\-]+)*"
    r"|serviceAccountName|serviceAccountNamespace)$")


def _string_read_paths(s: str, out: List[Path]) -> bool:
    """Collect resource paths a template string reads; False = some
    variable reads the resource in a way we cannot bound."""
    for m in _VAR_RE.finditer(s):
        expr = m.group(1).strip()
        om = _OBJ_PATH_RE.match(expr)
        if om is not None:
            out.append(tuple(om.group(1).split(".")))
            continue
        if expr == "request.namespace":
            out.append(("metadata", "namespace"))
            continue
        if _RESOURCE_FREE_RE.match(expr):
            continue
        return False  # functions, element.*, context vars, pipes, ...
    return True


def _walk_read_strings(node: Any, out: List[Path]) -> bool:
    if isinstance(node, dict):
        return all(_walk_read_strings(k, out) and _walk_read_strings(v, out)
                   for k, v in node.items())
    if isinstance(node, list):
        return all(_walk_read_strings(x, out) for x in node)
    if isinstance(node, str) and "{{" in node:
        return _string_read_paths(node, out)
    return True


def _match_block_reads(block, out: List[Path]) -> None:
    filters = list(block.any) + list(block.all)
    from ..api.policy import ResourceFilter

    if not filters and not block.resources.is_empty():
        filters = [ResourceFilter(resources=block.resources,
                                  user_info=block.user_info)]
    for f in filters:
        r = f.resources
        if r.name or r.names:
            out.append(("metadata", "name"))
        if r.namespaces:
            out.append(("metadata", "namespace"))
        if r.selector is not None:
            out.append(("metadata", "labels"))
        if r.namespace_selector is not None:
            out.append(("metadata", "namespace"))
        if r.annotations:
            out.append(("metadata", "annotations"))


def rule_read_paths(rule: Rule) -> PathSet:
    """Over-approximate resource paths the rule's triage predicate
    (match/exclude/preconditions) reads; None = unbounded."""
    try:
        out: List[Path] = [("kind",)]
        _match_block_reads(rule.match, out)
        _match_block_reads(rule.exclude, out)
        if rule.cel_preconditions:
            return None  # host-routed at compile anyway; stay safe
        if rule.preconditions is not None:
            if not _walk_read_strings(rule.preconditions, out):
                return None
        return out
    except Exception:  # noqa: BLE001
        return None


def paths_conflict(writes: PathSet, reads: PathSet) -> bool:
    """Does any written path prefix-overlap any read path (either
    direction)? None on either side = unbounded = conflict (except
    against a provably empty set)."""
    if reads is not None and not reads:
        return False
    if writes is not None and not writes:
        return False
    if writes is None or reads is None:
        return True
    for w in writes:
        for r in reads:
            if w[:len(r)] == r or r[:len(w)] == w:
                return True
    return False
