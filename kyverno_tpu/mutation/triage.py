"""Needs-mutation triage predicates.

A mutate rule's match/exclude/preconditions decide WHETHER the rule
applies; only then does the patch body matter. Triage reuses the
validate compiler wholesale by wrapping that predicate in a synthetic
``validate: {deny: {}}`` shell: an empty deny compiles to an
unconditionally-satisfied program, so the device verdict collapses to
the predicate itself —

    PASS / FAIL        -> rule applies (triage-positive)
    SKIP / NOT_MATCHED -> rule does not apply (triage-negative)
    ERROR / HOST       -> could not decide on device (host-routes)

``celPreconditions`` ride along in the synthetic dict on purpose: the
IR compiler raises ``Unsupported`` on them, which host-routes the rule
instead of silently dropping the condition.
"""

from __future__ import annotations

from typing import Any, Dict

from ..api.policy import ClusterPolicy, Rule

# predicate-relevant keys copied verbatim from the mutate rule's raw
# dict into the synthetic validate rule
_PREDICATE_KEYS = ("match", "exclude", "preconditions", "context",
                   "celPreconditions")


def triage_rule(rule: Rule) -> Rule:
    """Wrap a mutate rule's predicate in an empty-deny validate shell.

    The returned Rule compiles through ``tpu.ir.compile_rule`` exactly
    like a validate rule; its raw dict carries the original match /
    exclude / preconditions / context / celPreconditions so static
    context folding and unsupported-feature detection see the real
    predicate."""
    d: Dict[str, Any] = {"name": rule.name}
    raw = rule.raw or {}
    for key in _PREDICATE_KEYS:
        if raw.get(key) is not None:
            d[key] = raw[key]
    d["validate"] = {"deny": {}}
    return Rule.from_dict(d)


def synthetic_triage_policy(policy: ClusterPolicy) -> ClusterPolicy:
    """A ClusterPolicy whose rules are the triage shells of ``policy``'s
    mutate rules — the scalar oracle for triage verdicts (bench and
    shadow verification run it through ``Engine.validate``)."""
    meta = dict((policy.raw or {}).get("metadata") or {})
    meta["name"] = policy.name
    return ClusterPolicy.from_dict({
        "apiVersion": "kyverno.io/v1",
        "kind": "Policy" if policy.is_namespaced else "ClusterPolicy",
        "metadata": meta,
        "spec": {
            "validationFailureAction": "Enforce",
            "rules": [triage_rule(r).raw for r in policy.get_rules()
                      if r.has_mutate()],
        },
    })
