"""Native (C) components, compiled lazily with the system toolchain.

The runtime around the XLA compute path is allowed to be native; the
resource encoder is the scan pipeline's serial host bottleneck, so its
hot walk lives in fastencode.c (see that file's header for the parity
contract with the Python oracle). Build failures or
KYVERNO_TPU_NATIVE=0 degrade silently to the Python encoder —
correctness never depends on the toolchain."""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
from typing import Optional

_mod = None
_tried = False


def load() -> Optional[object]:
    """Compile (if stale) and import the _fastencode extension."""
    global _mod, _tried
    if _tried:
        return _mod
    _tried = True
    if os.environ.get("KYVERNO_TPU_NATIVE", "1") == "0":
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "fastencode.c")
    so = os.path.join(here, "_fastencode.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            inc = sysconfig.get_paths()["include"]
            cc = os.environ.get("CC", "gcc")
            tmp = so + f".tmp{os.getpid()}"
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", f"-I{inc}", src, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)  # atomic: concurrent builders race safely
        spec = importlib.util.spec_from_file_location("_fastencode", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
        _mod = mod
    except Exception:
        _mod = None
    return _mod
