/* fastencode — native resource->vocabulary encoder.
 *
 * C twin of kyverno_tpu/tpu/flatten.py encode_resources_vocab (the
 * parity oracle): walks resource dict trees with the CPython API and
 * produces the vocabulary batch form (row dedup + index tables).
 * The host encode is the scan pipeline's serial bottleneck — this
 * walk replaces ~7us/row of interpreter work with ~0.1us/row of C.
 *
 * Semantics are pinned to the Python encoder two ways:
 *  - the VALUE grammar (Go number/quantity/duration parsing, repr and
 *    sprint spellings — pattern.go:207-307 semantics) is NOT
 *    reimplemented: scalar-memo misses call back into Python
 *    _scalar_rec and the returned record is cached in C, so the hot
 *    path is native but the semantics come from one implementation;
 *  - paths/keys hash with the same tagged FNV-1a 64 stream
 *    (hashing.py), continued incrementally from the parent state.
 *
 * Process-lifetime memos (path edges, scalar records) mirror the
 * Python module-level memos; the row vocabulary is per call (per
 * tile), as in _finish_vocab.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ---------------- FNV-1a 64 (hashing.py) ---------------- */

#define FNV_OFFSET 0xCBF29CE484222325ULL
#define FNV_PRIME 0x100000001B3ULL
#define PATH_SEP 0x1f /* "\x1f" */

static uint64_t fnv1a(const unsigned char *d, Py_ssize_t n, uint64_t h) {
    for (Py_ssize_t i = 0; i < n; i++) h = (h ^ d[i]) * FNV_PRIME;
    return h;
}

static uint64_t hash_tagged(char tag, const unsigned char *d, Py_ssize_t n) {
    uint64_t h = FNV_OFFSET;
    h = (h ^ (unsigned char)tag) * FNV_PRIME;
    return fnv1a(d, n, h);
}

/* mix for internal hash tables (not semantic hashes) */
static uint64_t mix64(uint64_t x) {
    x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33; return x;
}

/* ---------------- scalar records ---------------- */

typedef struct {
    uint32_t repr_hi, repr_lo, sprint_hi, sprint_lo;
    uint32_t num_hi, num_lo, qty_hi, qty_lo, dur_hi, dur_lo;
    float num_val, qty_val, dur_val;
    uint8_t type_tag, bool_val;
    uint8_t has_repr, has_qty, has_dur, has_num;
    uint8_t str_goint, str_gofloat, has_glob;
    PyObject *rep; /* owned; repr string or NULL */
} ScalarRec;

/* Memo entries are INDIVIDUALLY heap-allocated and never move: walk()
 * and the per-call row vocabulary hold PathEntry / ScalarRec pointers
 * across table growth, so the hash tables store stable pointers
 * (growing reallocates only the pointer array). Both memos mirror the
 * Python module-level memos' cap-and-clear (flatten.py
 * _SCALAR_MEMO_CAP, _PathMemo.CAP): when a memo exceeds MEMO_CAP it is
 * cleared wholesale at the START of the next encode_vocab call — no
 * in-flight pointers exist then, and long-lived servers stop pinning
 * unbounded memory. */

#define MEMO_CAP (1u << 20)

typedef struct {
    PyObject *key;   /* owned value object */
    PyTypeObject *tp;
    uint64_t hash;
    ScalarRec rec;
} ScalarEntry;

static ScalarEntry **scalar_tab = NULL; /* open-addressed; NULL = empty */
static size_t scalar_cap = 0, scalar_len = 0;

/* ---------------- path memo ---------------- */

typedef struct {
    uint64_t parent_state;
    char *seg; Py_ssize_t seg_len; /* owned copy */
    uint64_t state;      /* norm hash of the child path */
    uint64_t key_hash;   /* hash_str(seg, tag="k") */
    uint8_t key_glob;
} PathEntry;

static PathEntry **path_tab = NULL; /* open-addressed; NULL = empty */
static size_t path_cap = 0, path_len = 0;

static uint64_t ROOT_STATE; /* fnv1a64(b"p") */

/* ---------------- growable tables ---------------- */

static uint64_t path_hash(uint64_t parent_state, const char *seg, Py_ssize_t n) {
    return mix64(parent_state ^ fnv1a((const unsigned char *)seg, n, FNV_OFFSET));
}

static int path_grow(void) {
    size_t ncap = path_cap ? path_cap * 2 : 4096;
    PathEntry **nt = calloc(ncap, sizeof(PathEntry *));
    if (!nt) return -1;
    for (size_t i = 0; i < path_cap; i++) {
        PathEntry *e = path_tab[i];
        if (!e) continue;
        size_t j = path_hash(e->parent_state, e->seg, e->seg_len) & (ncap - 1);
        while (nt[j]) j = (j + 1) & (ncap - 1);
        nt[j] = e;
    }
    free(path_tab); path_tab = nt; path_cap = ncap;
    return 0;
}

static void path_clear(void) {
    for (size_t i = 0; i < path_cap; i++) {
        if (path_tab[i]) { free(path_tab[i]->seg); free(path_tab[i]); path_tab[i] = NULL; }
    }
    path_len = 0;
}

static PathEntry *path_child(uint64_t parent_state, const char *seg, Py_ssize_t n) {
    if (!path_cap || path_len * 4 >= path_cap * 3) {
        if (path_grow() < 0) return NULL;
    }
    uint64_t h = path_hash(parent_state, seg, n);
    size_t j = h & (path_cap - 1);
    while (path_tab[j]) {
        PathEntry *e = path_tab[j];
        if (e->parent_state == parent_state && e->seg_len == n &&
            memcmp(e->seg, seg, (size_t)n) == 0)
            return e;
        j = (j + 1) & (path_cap - 1);
    }
    PathEntry *e = malloc(sizeof(PathEntry));
    if (!e) return NULL;
    e->seg = malloc((size_t)n + 1);
    if (!e->seg) { free(e); return NULL; }
    memcpy(e->seg, seg, (size_t)n); e->seg[n] = 0;
    e->seg_len = n;
    e->parent_state = parent_state;
    /* continue the FNV stream: SEP + seg, except for root children */
    uint64_t st = parent_state;
    if (parent_state != ROOT_STATE) {
        unsigned char sep = PATH_SEP;
        st = fnv1a(&sep, 1, st);
    }
    st = fnv1a((const unsigned char *)seg, n, st);
    e->state = st;
    e->key_hash = hash_tagged('k', (const unsigned char *)seg, n);
    e->key_glob = 0;
    if (!(n == 2 && seg[0] == '[' && seg[1] == ']')) {
        for (Py_ssize_t i = 0; i < n; i++)
            if (seg[i] == '*' || seg[i] == '?') { e->key_glob = 1; break; }
    }
    path_tab[j] = e;
    path_len++;
    return e;
}

/* ---------------- scalar memo ---------------- */

static int scalar_grow(void) {
    size_t ncap = scalar_cap ? scalar_cap * 2 : 4096;
    ScalarEntry **nt = calloc(ncap, sizeof(ScalarEntry *));
    if (!nt) return -1;
    for (size_t i = 0; i < scalar_cap; i++) {
        ScalarEntry *e = scalar_tab[i];
        if (!e) continue;
        size_t j = e->hash & (ncap - 1);
        while (nt[j]) j = (j + 1) & (ncap - 1);
        nt[j] = e;
    }
    free(scalar_tab); scalar_tab = nt; scalar_cap = ncap;
    return 0;
}

static void scalar_clear(void) {
    for (size_t i = 0; i < scalar_cap; i++) {
        ScalarEntry *e = scalar_tab[i];
        if (e) {
            Py_DECREF(e->key);
            Py_XDECREF(e->rec.rep);
            free(e);
            scalar_tab[i] = NULL;
        }
    }
    scalar_len = 0;
}

/* parse the 24-tuple _scalar_rec returns into a ScalarRec.
 * Order: type_tag, bool_val, arr_len, has_repr, repr_hi, repr_lo,
 * sprint_hi, sprint_lo, has_num, num_hi, num_lo, num_val, has_qty,
 * qty_hi, qty_lo, qty_val, has_dur, dur_hi, dur_lo, dur_val,
 * str_goint, str_gofloat, has_glob, rep */
static int parse_rec(PyObject *t, ScalarRec *r) {
    if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 24) {
        PyErr_SetString(PyExc_TypeError, "scalar_cb must return a 24-tuple");
        return -1;
    }
#define U32(i) ((uint32_t)PyLong_AsUnsignedLongLong(PyTuple_GET_ITEM(t, (i))))
#define U8(i) ((uint8_t)PyLong_AsLong(PyTuple_GET_ITEM(t, (i))))
#define F32(i) ((float)PyFloat_AsDouble(PyTuple_GET_ITEM(t, (i))))
    r->type_tag = U8(0); r->bool_val = U8(1);
    r->has_repr = U8(3); r->repr_hi = U32(4); r->repr_lo = U32(5);
    r->sprint_hi = U32(6); r->sprint_lo = U32(7);
    r->has_num = U8(8); r->num_hi = U32(9); r->num_lo = U32(10); r->num_val = F32(11);
    r->has_qty = U8(12); r->qty_hi = U32(13); r->qty_lo = U32(14); r->qty_val = F32(15);
    r->has_dur = U8(16); r->dur_hi = U32(17); r->dur_lo = U32(18); r->dur_val = F32(19);
    r->str_goint = U8(20); r->str_gofloat = U8(21); r->has_glob = U8(22);
#undef U32
#undef U8
#undef F32
    PyObject *rep = PyTuple_GET_ITEM(t, 23);
    if (rep == Py_None) r->rep = NULL;
    else { Py_INCREF(rep); r->rep = rep; }
    if (PyErr_Occurred()) return -1;
    return 0;
}

static uint64_t scalar_hash(PyObject *v, int *hashable) {
    *hashable = 1;
    if (v == Py_None) return 0x9e3779b97f4a7c15ULL;
    if (PyBool_Check(v)) return v == Py_True ? 0xa5a5a5a5a5a5a5a5ULL : 0x5a5a5a5a5a5a5a5aULL;
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t bits; memcpy(&bits, &d, 8);
        return mix64(bits ^ 0xf10a7);
    }
    Py_hash_t h = PyObject_Hash(v);
    if (h == -1) { PyErr_Clear(); *hashable = 0; return 0; }
    return mix64((uint64_t)h ^ ((uintptr_t)Py_TYPE(v) >> 4));
}

/* returns the memoized record for a scalar value, calling cb on miss.
 * On unhashable values, fills *tmp and returns tmp (not memoized). */
static ScalarRec *scalar_lookup(PyObject *v, PyObject *cb, ScalarRec *tmp) {
    int hashable;
    uint64_t h = scalar_hash(v, &hashable);
    size_t j = 0;
    if (hashable && scalar_cap) {
        j = h & (scalar_cap - 1);
        while (scalar_tab[j]) {
            ScalarEntry *e = scalar_tab[j];
            if (e->hash == h && e->tp == Py_TYPE(v)) {
                if (e->key == v) return &e->rec;
                if (PyFloat_CheckExact(v)) {
                    double a = PyFloat_AS_DOUBLE(v), b = PyFloat_AS_DOUBLE(e->key);
                    uint64_t ba, bb; memcpy(&ba, &a, 8); memcpy(&bb, &b, 8);
                    if (ba == bb) return &e->rec;
                } else {
                    int eq = PyObject_RichCompareBool(v, e->key, Py_EQ);
                    if (eq < 0) { PyErr_Clear(); }
                    else if (eq) return &e->rec;
                }
            }
            j = (j + 1) & (scalar_cap - 1);
        }
    }
    PyObject *t = PyObject_CallFunctionObjArgs(cb, v, NULL);
    if (!t) return NULL;
    ScalarRec rec;
    if (parse_rec(t, &rec) < 0) { Py_DECREF(t); return NULL; }
    Py_DECREF(t);
    if (!hashable) { *tmp = rec; return tmp; }
    if (!scalar_cap || scalar_len * 4 >= scalar_cap * 3) {
        if (scalar_grow() < 0) return NULL;
        j = h & (scalar_cap - 1);
        while (scalar_tab[j]) j = (j + 1) & (scalar_cap - 1);
    }
    ScalarEntry *e = malloc(sizeof(ScalarEntry));
    if (!e) { Py_XDECREF(rec.rep); PyErr_NoMemory(); return NULL; }
    Py_INCREF(v);
    e->key = v; e->tp = Py_TYPE(v); e->hash = h; e->rec = rec;
    scalar_tab[j] = e;
    scalar_len++;
    return &e->rec;
}

/* ---------------- per-call encode state ---------------- */

#define T_NULL 0
#define T_BOOL 1
#define T_NUM 2
#define T_STR 3
#define T_MAP 4
#define T_ARR 5

typedef struct {
    uint64_t norm, parent, keyh;
    float arr_len;
    int32_t scope1, scope2, byte_slot, key_byte_slot;
    uint8_t key_glob, s2_overflow, type_tag;
    ScalarRec *sc;   /* NULL for containers; identity = dedup key part */
    ScalarRec inl;   /* storage for unhashable scalars */
    uint8_t sc_inline; /* sc points at inl (compare by value not ptr) */
} TmpRow;

typedef struct {
    int64_t *vals; /* vocab row ids; mirrors vocab_rows list, id = idx+1 */
    uint64_t *hashes;
    size_t *idx_tab; size_t tab_cap;
    TmpRow *rows; size_t len, cap;
} Vocab;

typedef struct {
    PyObject *cb;
    const uint64_t *byte_paths; Py_ssize_t n_byte_paths;
    const uint64_t *key_byte_paths; Py_ssize_t n_key_byte_paths;
    int max_rows, max_instances, pool_slots, pool_width;
    /* per-resource */
    TmpRow *tmp; int row; int pool_used; int ok;
    int32_t *pool_sidx_row;
    /* pool string table (per call) */
    PyObject *pool_strs;       /* list[bytes]; id 0 = b"" */
    PyObject *pool_sid_map;    /* dict bytes -> int id */
    Vocab voc;
    int err;
} Enc;

static int binsearch(const uint64_t *a, Py_ssize_t n, uint64_t x) {
    Py_ssize_t lo = 0, hi = n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        if (a[mid] < x) lo = mid + 1; else hi = mid;
    }
    return lo < n && a[lo] == x;
}

/* assign a pool slot for utf8 bytes; returns slot or -1 (overflow ->
 * e->ok = 0, matching _FastEncoder._assign_pool) */
static int assign_pool(Enc *e, const char *data, Py_ssize_t n) {
    if (n > e->pool_width || e->pool_used >= e->pool_slots) { e->ok = 0; return -1; }
    int slot = e->pool_used++;
    PyObject *b = PyBytes_FromStringAndSize(data, n);
    if (!b) { e->err = 1; return -1; }
    PyObject *sid = PyDict_GetItem(e->pool_sid_map, b); /* borrowed */
    long id;
    if (sid) { id = PyLong_AsLong(sid); Py_DECREF(b); }
    else {
        id = (long)PyList_GET_SIZE(e->pool_strs);
        PyObject *idob = PyLong_FromLong(id);
        if (!idob || PyList_Append(e->pool_strs, b) < 0 ||
            PyDict_SetItem(e->pool_sid_map, b, idob) < 0) {
            Py_XDECREF(idob); Py_DECREF(b); e->err = 1; return -1;
        }
        Py_DECREF(idob); Py_DECREF(b);
    }
    e->pool_sidx_row[slot] = (int32_t)id;
    return slot;
}

/* walk: returns tmp-row index, or -1 when the row cap is hit */
static int walk(Enc *e, PyObject *node, PathEntry *pe, uint64_t state,
                uint64_t norm, uint64_t parent, uint64_t keyh, uint8_t kglob,
                int scope1, int scope2, int depth) {
    if (e->err) return -1;
    if (e->row >= e->max_rows) { e->ok = 0; return -1; }
    int r = e->row++;
    TmpRow *t = &e->tmp[r];
    memset(t, 0, sizeof(TmpRow));
    t->norm = norm; t->parent = parent; t->keyh = keyh; t->key_glob = kglob;
    t->scope1 = scope1; t->scope2 = scope2;
    t->byte_slot = -1; t->key_byte_slot = -1;

    if (PyDict_Check(node)) {
        t->type_tag = T_MAP;
        t->arr_len = (float)PyDict_GET_SIZE(node);
        int pool_keys = binsearch(e->key_byte_paths, e->n_key_byte_paths, norm);
        PyObject *k, *v; Py_ssize_t pos = 0;
        while (PyDict_Next(node, &pos, &k, &v)) {
            PyObject *ks = k;
            int dec = 0;
            if (!PyUnicode_CheckExact(k)) {
                ks = PyObject_Str(k);
                if (!ks) { e->err = 1; return r; }
                dec = 1;
            }
            Py_ssize_t sl; const char *sd = PyUnicode_AsUTF8AndSize(ks, &sl);
            if (!sd) { if (dec) Py_DECREF(ks); e->err = 1; return r; }
            PathEntry *ce = path_child(state, sd, sl);
            if (!ce) { if (dec) Py_DECREF(ks); e->err = 1; return r; }
            int cr = walk(e, v, ce, ce->state, ce->state, norm, ce->key_hash,
                          ce->key_glob, scope1, scope2, depth);
            if (e->err) { if (dec) Py_DECREF(ks); return r; }
            if (pool_keys && cr >= 0) {
                int slot = assign_pool(e, sd, sl);
                if (e->err) { if (dec) Py_DECREF(ks); return r; }
                if (slot >= 0) e->tmp[cr].key_byte_slot = slot;
                if (PyUnicode_Check(v) && e->tmp[cr].byte_slot < 0) {
                    Py_ssize_t vl; const char *vd = PyUnicode_AsUTF8AndSize(v, &vl);
                    if (!vd) { if (dec) Py_DECREF(ks); e->err = 1; return r; }
                    int vslot = assign_pool(e, vd, vl);
                    if (e->err) { if (dec) Py_DECREF(ks); return r; }
                    if (vslot >= 0) e->tmp[cr].byte_slot = vslot;
                }
            }
            if (dec) Py_DECREF(ks);
        }
    } else if (PyList_Check(node)) {
        Py_ssize_t n = PyList_GET_SIZE(node);
        t->type_tag = T_ARR;
        t->arr_len = (float)n;
        if (n > e->max_instances) {
            if (depth == 0) e->ok = 0;
            else if (depth == 1) t->s2_overflow = 1;
        }
        PathEntry *ce = path_child(state, "[]", 2);
        if (!ce) { e->err = 1; return r; }
        for (Py_ssize_t i = 0; i < n; i++) {
            int s1 = scope1, s2 = scope2;
            if (depth == 0) s1 = (int)i;
            else if (depth == 1) s2 = (int)i;
            walk(e, PyList_GET_ITEM(node, i), ce, ce->state, ce->state, norm,
                 ce->key_hash, ce->key_glob, s1, s2, depth + 1);
            if (e->err) return r;
        }
    } else {
        ScalarRec *sc = scalar_lookup(node, e->cb, &t->inl);
        if (!sc) { e->err = 1; return r; }
        t->sc = sc;
        t->sc_inline = (sc == &t->inl);
        t->type_tag = sc->type_tag;
        if (sc->has_repr && binsearch(e->byte_paths, e->n_byte_paths, norm)) {
            Py_ssize_t rl; const char *rd = PyUnicode_AsUTF8AndSize(sc->rep, &rl);
            if (!rd) { e->err = 1; return r; }
            int slot = assign_pool(e, rd, rl);
            if (slot >= 0) t->byte_slot = slot;
        }
    }
    return r;
}

/* ---------------- row vocabulary ---------------- */

static uint64_t row_hash(const TmpRow *t) {
    uint64_t h = t->norm;
    h = mix64(h ^ ((uint64_t)(uint32_t)t->scope1 | ((uint64_t)(uint32_t)t->scope2 << 32)));
    h = mix64(h ^ ((uint64_t)(uint32_t)t->byte_slot | ((uint64_t)(uint32_t)t->key_byte_slot << 32)));
    h ^= (uint64_t)t->s2_overflow << 7;
    if (t->sc) h = mix64(h ^ (t->sc_inline ? 0x51ed2705 : (uint64_t)(uintptr_t)t->sc));
    else {
        uint32_t al; memcpy(&al, &t->arr_len, 4);
        h = mix64(h ^ ((uint64_t)t->type_tag << 32) ^ al);
    }
    return h;
}

static int row_eq(const TmpRow *a, const TmpRow *b) {
    if (a->norm != b->norm || a->scope1 != b->scope1 || a->scope2 != b->scope2 ||
        a->s2_overflow != b->s2_overflow || a->byte_slot != b->byte_slot ||
        a->key_byte_slot != b->key_byte_slot || a->type_tag != b->type_tag)
        return 0;
    if (a->sc && b->sc) {
        if (a->sc_inline || b->sc_inline) return 0; /* unhashable: never dedup */
        return a->sc == b->sc;
    }
    if (a->sc || b->sc) return 0;
    return a->arr_len == b->arr_len;
}

static int voc_grow(Vocab *v) {
    size_t ncap = v->tab_cap ? v->tab_cap * 2 : 8192;
    size_t *nt = malloc(ncap * sizeof(size_t));
    if (!nt) return -1;
    memset(nt, 0xff, ncap * sizeof(size_t));
    for (size_t i = 0; i < v->tab_cap; i++) {
        size_t ri = v->idx_tab ? v->idx_tab[i] : (size_t)-1;
        if (ri == (size_t)-1) continue;
        size_t j = v->hashes[ri] & (ncap - 1);
        while (nt[j] != (size_t)-1) j = (j + 1) & (ncap - 1);
        nt[j] = ri;
    }
    free(v->idx_tab); v->idx_tab = nt; v->tab_cap = ncap;
    return 0;
}

static int64_t voc_intern(Vocab *v, const TmpRow *t) {
    if (!v->tab_cap || v->len * 4 >= v->tab_cap * 3) {
        if (voc_grow(v) < 0) return -1;
    }
    uint64_t h = row_hash(t);
    size_t j = h & (v->tab_cap - 1);
    int dedupable = !(t->sc && t->sc_inline);
    while (v->idx_tab[j] != (size_t)-1) {
        size_t ri = v->idx_tab[j];
        if (dedupable && v->hashes[ri] == h && row_eq(&v->rows[ri], t))
            return (int64_t)ri + 1;
        j = (j + 1) & (v->tab_cap - 1);
    }
    if (v->len >= v->cap) {
        size_t ncap = v->cap ? v->cap * 2 : 4096;
        TmpRow *nr = realloc(v->rows, ncap * sizeof(TmpRow));
        if (!nr) return -1;
        v->rows = nr;
        uint64_t *nh = realloc(v->hashes, ncap * sizeof(uint64_t));
        if (!nh) return -1;
        v->hashes = nh; v->cap = ncap;
    }
    size_t ri = v->len++;
    v->rows[ri] = *t;
    /* unhashable scalars carry an inline rec in the (reused) TmpRow;
     * the vocab copy needs its own heap-stable rec — v->rows itself
     * moves on realloc, so pointing into the array would dangle.
     * Ownership of rec.rep moves to the heap copy; freed (with a
     * rep decref) at encode teardown via the sc_inline marker. */
    if (t->sc && t->sc_inline) {
        ScalarRec *cp = malloc(sizeof(ScalarRec));
        if (!cp) { v->len--; return -1; }
        *cp = *t->sc;
        v->rows[ri].sc = cp;
    }
    v->hashes[ri] = h;
    v->idx_tab[j] = ri;
    return (int64_t)ri + 1;
}

/* build the 35-tuple for one vocab row (order documented in flatten.py
 * encode_resources_vocab native glue) */
static PyObject *row_tuple(const TmpRow *t) {
    ScalarRec z; memset(&z, 0, sizeof z);
    const ScalarRec *s = t->sc ? t->sc : &z;
    return Py_BuildValue(
        "(IIIIIIIIIIIIIIIIffffiiiibbbbbbbbbbb)",
        (unsigned)(t->norm >> 32), (unsigned)(t->norm & 0xffffffffu),
        (unsigned)(t->parent >> 32), (unsigned)(t->parent & 0xffffffffu),
        (unsigned)(t->keyh >> 32), (unsigned)(t->keyh & 0xffffffffu),
        (unsigned)s->repr_hi, (unsigned)s->repr_lo,
        (unsigned)s->qty_hi, (unsigned)s->qty_lo,
        (unsigned)s->dur_hi, (unsigned)s->dur_lo,
        (unsigned)s->num_hi, (unsigned)s->num_lo,
        (unsigned)s->sprint_hi, (unsigned)s->sprint_lo,
        (double)s->num_val, (double)s->qty_val, (double)s->dur_val,
        (double)t->arr_len,
        (int)t->scope1, (int)t->scope2, (int)t->byte_slot, (int)t->key_byte_slot,
        (int)t->type_tag, (int)s->bool_val, (int)s->has_repr, (int)s->has_qty,
        (int)s->has_dur, (int)s->has_num, (int)s->str_goint, (int)s->str_gofloat,
        (int)s->has_glob, (int)t->key_glob, (int)t->s2_overflow);
}

/* ---------------- entry point ---------------- */

static PyObject *encode_vocab(PyObject *self, PyObject *args) {
    PyObject *resources, *cb;
    int max_rows, max_instances, pool_slots, pool_width;
    Py_buffer bp_buf, kbp_buf, row_idx_buf, n_rows_buf, fb_buf, psx_buf;
    if (!PyArg_ParseTuple(args, "Oiiiiy*y*Ow*w*w*w*",
                          &resources, &max_rows, &max_instances, &pool_slots,
                          &pool_width, &bp_buf, &kbp_buf, &cb,
                          &row_idx_buf, &n_rows_buf, &fb_buf, &psx_buf))
        return NULL;
    /* cap-and-clear between calls (Python memo CAP semantics): no
     * in-flight pointers into the memos exist at call boundaries */
    if (scalar_len >= MEMO_CAP) scalar_clear();
    if (path_len >= MEMO_CAP) path_clear();
    PyObject *result = NULL;
    Enc e; memset(&e, 0, sizeof e);
    e.cb = cb;
    e.byte_paths = (const uint64_t *)bp_buf.buf;
    e.n_byte_paths = bp_buf.len / 8;
    e.key_byte_paths = (const uint64_t *)kbp_buf.buf;
    e.n_key_byte_paths = kbp_buf.len / 8;
    e.max_rows = max_rows; e.max_instances = max_instances;
    e.pool_slots = pool_slots; e.pool_width = pool_width;
    e.tmp = malloc((size_t)max_rows * sizeof(TmpRow));
    e.pool_strs = PyList_New(0);
    e.pool_sid_map = PyDict_New();
    PyObject *empty = PyBytes_FromStringAndSize("", 0);
    if (!e.tmp || !e.pool_strs || !e.pool_sid_map || !empty) goto done;
    {
        PyObject *zero = PyLong_FromLong(0);
        if (!zero || PyList_Append(e.pool_strs, empty) < 0 ||
            PyDict_SetItem(e.pool_sid_map, empty, zero) < 0) { Py_XDECREF(zero); goto done; }
        Py_DECREF(zero);
    }

    if (!PyList_Check(resources)) {
        PyErr_SetString(PyExc_TypeError, "resources must be a list");
        goto done;
    }
    Py_ssize_t n = PyList_GET_SIZE(resources);
    int32_t *row_idx = (int32_t *)row_idx_buf.buf;      /* (n, max_rows) */
    int32_t *n_rows = (int32_t *)n_rows_buf.buf;        /* (n,) */
    uint8_t *fallback = (uint8_t *)fb_buf.buf;          /* (n,) */
    int32_t *pool_sidx = (int32_t *)psx_buf.buf;        /* (n, pool_slots) */

    for (Py_ssize_t i = 0; i < n; i++) {
        e.row = 0; e.pool_used = 0; e.ok = 1;
        e.pool_sidx_row = pool_sidx + i * pool_slots;
        PyObject *res = PyList_GET_ITEM(resources, i);
        walk(&e, res, NULL, ROOT_STATE, ROOT_STATE, 0, 0, 0, -1, -1, 0);
        if (e.err || PyErr_Occurred()) goto done;
        n_rows[i] = e.row;
        fallback[i] = e.ok ? 0 : 1;
        int32_t *out = row_idx + i * max_rows;
        for (int r = 0; r < e.row; r++) {
            int64_t id = voc_intern(&e.voc, &e.tmp[r]);
            if (id < 0) { PyErr_NoMemory(); goto done; }
            out[r] = (int32_t)id;
        }
    }

    {
        PyObject *vrows = PyList_New((Py_ssize_t)e.voc.len);
        if (!vrows) goto done;
        for (size_t ri = 0; ri < e.voc.len; ri++) {
            PyObject *t = row_tuple(&e.voc.rows[ri]);
            if (!t) { Py_DECREF(vrows); goto done; }
            PyList_SET_ITEM(vrows, (Py_ssize_t)ri, t);
        }
        result = PyTuple_Pack(2, vrows, e.pool_strs);
        Py_DECREF(vrows);
    }

done:
    Py_XDECREF(empty);
    Py_XDECREF(e.pool_strs);
    Py_XDECREF(e.pool_sid_map);
    free(e.tmp);
    for (size_t ri = 0; ri < e.voc.len; ri++) {
        TmpRow *t = &e.voc.rows[ri];
        if (t->sc_inline && t->sc) { Py_XDECREF(t->sc->rep); free(t->sc); }
    }
    free(e.voc.rows); free(e.voc.hashes); free(e.voc.idx_tab);
    PyBuffer_Release(&bp_buf); PyBuffer_Release(&kbp_buf);
    PyBuffer_Release(&row_idx_buf); PyBuffer_Release(&n_rows_buf);
    PyBuffer_Release(&fb_buf); PyBuffer_Release(&psx_buf);
    if (!result && !PyErr_Occurred())
        PyErr_SetString(PyExc_RuntimeError, "fastencode internal error");
    return result;
}

static PyObject *memo_sizes(PyObject *self, PyObject *args) {
    return Py_BuildValue("(nn)", (Py_ssize_t)path_len, (Py_ssize_t)scalar_len);
}

static PyMethodDef methods[] = {
    {"encode_vocab", encode_vocab, METH_VARARGS,
     "encode_vocab(resources, max_rows, max_instances, pool_slots, pool_width, "
     "byte_paths_u64, key_byte_paths_u64, scalar_cb, row_idx, n_rows, fallback, "
     "pool_sidx) -> (vocab_rows, pool_strs)"},
    {"memo_sizes", memo_sizes, METH_NOARGS, "(path_memo_len, scalar_memo_len)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastencode", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__fastencode(void) {
    unsigned char p = 'p';
    ROOT_STATE = fnv1a(&p, 1, FNV_OFFSET);
    return PyModule_Create(&moduledef);
}
