"""Observability: events, metrics, tracing (pkg/event, pkg/metrics,
pkg/tracing equivalents)."""

from .events import Event, EventGenerator
from .metrics import MetricsRegistry, global_registry
from .tracing import Span, Tracer
