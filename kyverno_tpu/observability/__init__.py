"""Observability: events, metrics, tracing, profiling (pkg/event,
pkg/metrics, pkg/tracing equivalents + the SURVEY §5 phase split)."""

from .events import Event, EventGenerator
from .metrics import MetricsRegistry, global_registry
from .profiling import PhaseProfiler, global_profiler
from .tracing import (OTLPJsonFileExporter, Span, SpanContext, Tracer,
                      global_tracer)
