"""Observability: events, metrics, tracing, profiling (pkg/event,
pkg/metrics, pkg/tracing equivalents + the SURVEY §5 phase split) and
the policy observatory (analytics: per-rule stats, feed starvation,
SLO burn rates)."""

from .analytics import (RuleIdent, RuleStatsAccumulator, SloTracker,
                        StarvationTracker, global_rule_stats, global_slo,
                        global_starvation)
from .events import Event, EventGenerator
from .metrics import MetricsRegistry, global_registry
from .profiling import PhaseProfiler, global_profiler
from .tracing import (OTLPJsonFileExporter, Span, SpanContext, Tracer,
                      global_tracer)
