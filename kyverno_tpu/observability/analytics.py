"""Policy observatory — workload-level analytics over the dispatch ladder.

PR 3 gave each *request* a trace; this module answers *workload*
questions: which rules are hot, which never fire, how much of the
policy set actually runs on device, is the TPU starving while the host
encodes, and are we burning the latency/freshness error budgets?

Three connected pieces:

- **RuleStatsAccumulator** — exact per-rule verdict counts (pass /
  skip / fail / not-matched / error) across EVERY path a verdict can
  take: device dispatch (where the compiled program reduces the counts
  on device, O(rules) readback), host-cell completion, scalar and
  breaker fallback, quarantine, the pipelined scanner, and
  verdict-cache hits (replayed so cached work still counts). Keyed by
  a per-policy content hash over the policy SPEC, so stats survive
  snapshot swaps, no-op re-applies, and renames.

- **StarvationTracker** — rolling-window device feed accounting: the
  fraction of device-relevant wall time the device sat idle waiting on
  host encode. This is the target metric for the encode-pool work
  (ROADMAP item 1: device capable of ~7.4B rule-evals/s, e2e bounded
  by ~927 res/s host encode).

- **SloTracker** — multi-window burn-rate tracking for the serving
  SLOs: admission p99 vs target, background-scan freshness, and the
  device-coverage floor. State lands on ``/readyz`` and the
  ``kyverno_slo_*`` gauges.

The module stays importable without jax (the CLI ``top`` view and the
metrics registry import it); verdict-code constants mirror
``tpu/evaluator.py`` and are asserted equal in tests.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

# verdict codes, tpu/evaluator.py order (mirrored, not imported: this
# module must not pull jax into metrics-only consumers)
PASS, SKIP, FAIL, NOT_MATCHED, ERROR, HOST, CONFIRM = 0, 1, 2, 3, 4, 5, 6
NUM_CLASSES = 7
CLASS_NAMES = ("pass", "skip", "fail", "not_matched", "error", "host",
               "confirm")


def class_counts(table: Any, num_classes: int = NUM_CLASSES) -> np.ndarray:
    """(rules, N) verdict table -> (rules, num_classes) per-class
    counts in ONE vectorized bincount — the host-side mirror of the
    device-side reduction the compiled program performs."""
    table = np.asarray(table)
    if table.ndim == 1:
        table = table.reshape(table.shape[0], 1) if table.size else \
            table.reshape(0, 1)
    d = table.shape[0]
    if table.size == 0:
        return np.zeros((d, num_classes), dtype=np.int64)
    idx = (table.astype(np.int64)
           + np.arange(d, dtype=np.int64)[:, None] * num_classes)
    return np.bincount(idx.ravel(),
                       minlength=d * num_classes).reshape(d, num_classes)


def policy_spec_hash(policy: Any) -> str:
    """Analytics identity of a policy: a content hash over the SPEC
    only (metadata excluded), so rule stats survive snapshot swaps,
    no-op re-applies, AND renames — the entry retires naturally when
    the rule content itself changes.

    Content-addressed identity cuts both ways: two loaded policies
    with byte-identical specs are ONE logical rule set to the
    accumulator (same stance the verdict cache takes) — their counts
    merge under the most recently compiled display name."""
    raw = getattr(policy, "raw", None)
    if isinstance(raw, dict) and raw.get("spec") is not None:
        payload = json.dumps(raw.get("spec"), sort_keys=True, default=str)
    else:
        payload = repr(getattr(policy, "spec", None))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _static_status(policy: str, rule: str) -> Dict[str, Any]:
    """The static-analysis correlation for one never-fired rule: once
    the lifecycle lint has run, a never-fired /debug/rules entry says
    WHY — ``static: "dead"`` (can never fire), ``static:
    "shadowed_by"`` + ``by`` (another rule decides first), or
    ``static: "ok"`` (just no traffic yet). Empty before any analysis
    (or when the rule's match shape was not synthesizable), so the
    field's absence itself means "no static evidence". Lazy import:
    analysis/ pulls engine machinery this module must not load."""
    try:
        from ..analysis import global_analysis

        return global_analysis.static_for(policy, rule) or {}
    except Exception:
        return {}


class RuleIdent(NamedTuple):
    """Stable identity of one rule row in a compiled set."""

    policy_hash: str
    policy_name: str
    rule_name: str
    on_device: bool


class _RuleRecord:
    __slots__ = ("policy_hash", "policy_name", "rule_name", "on_device",
                 "counts", "by_source", "first_seen", "last_fired")

    def __init__(self, ident: RuleIdent, now: float):
        self.policy_hash = ident.policy_hash
        self.policy_name = ident.policy_name
        self.rule_name = ident.rule_name
        self.on_device = ident.on_device
        self.counts = np.zeros(NUM_CLASSES, dtype=np.int64)
        self.by_source: Dict[str, int] = {}
        self.first_seen = now
        self.last_fired: Optional[float] = None

    def fired(self) -> int:
        return int(self.counts[PASS] + self.counts[FAIL] + self.counts[ERROR])


class RuleStatsAccumulator:
    """Process-wide per-rule verdict accounting. Thread-safe; every
    ingest point hands a counts matrix aligned with a rule-ident list,
    so the accumulator itself never walks verdict tables."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._records: Dict[Tuple[str, str], _RuleRecord] = {}  # guarded-by: _lock
        self.enabled = os.environ.get(
            "KYVERNO_TPU_RULE_STATS", "1").lower() not in ("0", "false", "off")

    # -- write side

    def _rec_locked(self, ident: RuleIdent, now: float) -> _RuleRecord:
        key = (ident.policy_hash, ident.rule_name)
        rec = self._records.get(key)
        if rec is None:
            rec = _RuleRecord(ident, now)
            self._records[key] = rec
        else:
            # latest compile wins for display name + device placement
            rec.policy_name = ident.policy_name
            rec.on_device = ident.on_device
        return rec

    def register(self, idents: Sequence[RuleIdent]) -> None:
        """Make rules visible (never-fired tracking starts at first
        registration — compile time, not first evaluation)."""
        if not self.enabled or not idents:
            return
        now = self._clock()
        with self._lock:
            for ident in idents:
                self._rec_locked(ident, now)

    def ingest_counts(self, idents: Sequence[RuleIdent], counts: Any,
                      source: str = "device") -> None:
        """``counts``: (len(idents), >=5) per-class totals in verdict-
        code order. The one write path every ladder rung funnels into."""
        if not self.enabled or not len(idents):
            return
        counts = np.asarray(counts, dtype=np.int64)
        now = self._clock()
        with self._lock:
            for ri, ident in enumerate(idents):
                row = counts[ri]
                rec = self._rec_locked(ident, now)
                rec.counts[: row.shape[0]] += row
                evals = int(row.sum())
                if evals:
                    rec.by_source[source] = rec.by_source.get(source, 0) + evals
                if int(row[PASS]) + int(row[FAIL]) + int(row[ERROR]):
                    rec.last_fired = now

    def ingest_table(self, idents: Sequence[RuleIdent], table: Any,
                     source: str = "host") -> None:
        if not self.enabled or not len(idents):
            return
        self.ingest_counts(idents, class_counts(table), source=source)

    def ingest_column(self, idents: Sequence[RuleIdent], column: Any,
                      source: str = "cached") -> None:
        if not self.enabled or not len(idents):
            return
        col = np.asarray(column).reshape(len(idents), 1)
        self.ingest_counts(idents, class_counts(col), source=source)

    def reset(self) -> None:
        with self._lock:
            self._records.clear()

    # -- read side

    def _snapshot(self) -> List[_RuleRecord]:
        with self._lock:
            return list(self._records.values())

    def rules_tracked(self) -> int:
        with self._lock:
            return len(self._records)

    def rule_rows(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = self._clock() if now is None else now
        rows = []
        with self._lock:
            for rec in self._records.values():
                c = rec.counts
                rows.append({
                    "policy": rec.policy_name,
                    "rule": rec.rule_name,
                    "policy_hash": rec.policy_hash,
                    "on_device": rec.on_device,
                    "evals": int(c.sum()),
                    "fired": rec.fired(),
                    "pass": int(c[PASS]),
                    "skip": int(c[SKIP]),
                    "fail": int(c[FAIL]),
                    "not_matched": int(c[NOT_MATCHED]),
                    "error": int(c[ERROR]),
                    "by_source": dict(rec.by_source),
                    "age_s": round(max(0.0, now - rec.first_seen), 3),
                    "last_fired_age_s": (
                        round(max(0.0, now - rec.last_fired), 3)
                        if rec.last_fired is not None else None),
                })
        return rows

    def report(self, top: int = 20, now: Optional[float] = None
               ) -> Dict[str, Any]:
        """The /debug/rules document: top-N hot rules, never-fired
        rules with age, per-policy device coverage."""
        rows = self.rule_rows(now=now)
        hot = sorted((r for r in rows if r["fired"]),
                     key=lambda r: (-r["fired"], r["policy"], r["rule"]))
        never = sorted((r for r in rows if not r["fired"]),
                       key=lambda r: (-r["age_s"], r["policy"], r["rule"]))
        return {
            "rules_tracked": len(rows),
            "top": hot[: max(top, 0)],
            "never_fired": [
                {"policy": r["policy"], "rule": r["rule"],
                 "policy_hash": r["policy_hash"], "age_s": r["age_s"],
                 "on_device": r["on_device"], "evals": r["evals"],
                 **_static_status(r["policy"], r["rule"])}
                for r in never],
            "policies": self.policy_aggregates(),
        }

    def policy_aggregates(self) -> List[Dict[str, Any]]:
        """Per-policy rollup (by display name — the Prometheus label)."""
        agg: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for rec in self._records.values():
                a = agg.setdefault(rec.policy_name, {
                    "policy": rec.policy_name, "rules": 0, "device_rules": 0,
                    "evals": 0, "fired": 0, "fails": 0, "never_fired": 0})
                a["rules"] += 1
                a["device_rules"] += 1 if rec.on_device else 0
                a["evals"] += int(rec.counts.sum())
                fired = rec.fired()
                a["fired"] += fired
                a["fails"] += int(rec.counts[FAIL])
                a["never_fired"] += 0 if fired else 1
        out = []
        pattern_cells = global_pattern_cells.per_policy()
        for a in agg.values():
            a["device_coverage"] = round(
                a["device_rules"] / a["rules"], 4) if a["rules"] else 0.0
            pc = pattern_cells.get(a["policy"])
            if pc:
                # pattern host cells vs other host cells: the pattern
                # block isolates how much host work is pattern-caused
                a["pattern_cells"] = pc
            out.append(a)
        return sorted(out, key=lambda a: (-a["evals"], a["policy"]))

    def render_table(self, top: int = 20,
                     title: str = "per-rule analytics") -> str:
        """Aligned text table (`apply --rule-stats`)."""
        rows = sorted(self.rule_rows(),
                      key=lambda r: (-r["fired"], -r["evals"],
                                     r["policy"], r["rule"]))
        if not rows:
            return f"{title}: no rules tracked"
        table = [("policy/rule", "evals", "pass", "fail", "error", "skip",
                  "fired", "where")]
        for r in rows[: max(top, 0)]:
            table.append((
                f"{r['policy']}/{r['rule']}", str(r["evals"]),
                str(r["pass"]), str(r["fail"]), str(r["error"]),
                str(r["skip"]),
                "never" if not r["fired"] else str(r["fired"]),
                "device" if r["on_device"] else "host"))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(table[0]))]
        lines = [title]
        for i, row in enumerate(table):
            lines.append("  ".join(c.ljust(w)
                                   for c, w in zip(row, widths)).rstrip())
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        never = [r for r in rows if not r["fired"]]
        if never:
            lines.append(f"never fired: {len(never)} rule(s): " + ", ".join(
                f"{r['policy']}/{r['rule']}" for r in never[:10]))
        return "\n".join(lines)


global_rule_stats = RuleStatsAccumulator()


class PatternCellTracker:
    """Process-wide accounting of pattern-bearing cells by resolution
    path (tpu/dfa.py ladder): ``device`` — the DFA verdict stood,
    ``confirm`` — an approximate/byte-sensitive hit was confirmed by
    the scalar oracle, ``host`` — a non-lowerable pattern kept the
    whole cell on the host route. Feeds
    kyverno_tpu_pattern_cells_total and the /debug/rules per-policy
    coverage breakdown (pattern host cells vs other host cells)."""

    PATHS = ("device", "confirm", "host")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._per_policy: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock

    def record(self, policy: str, device: int = 0, confirm: int = 0,
               host: int = 0) -> None:
        if not (device or confirm or host):
            return
        with self._lock:
            d = self._per_policy.setdefault(
                policy, {"device": 0, "confirm": 0, "host": 0})
            d["device"] += int(device)
            d["confirm"] += int(confirm)
            d["host"] += int(host)
        try:
            from .metrics import global_registry as reg

            for path, v in (("device", device), ("confirm", confirm),
                            ("host", host)):
                if v:
                    reg.pattern_cells.inc({"path": path}, int(v))
        except Exception:  # noqa: BLE001
            pass  # metrics must never block the verdict path

    def per_policy(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in self._per_policy.items()}

    def totals(self) -> Dict[str, int]:
        out = {p: 0 for p in self.PATHS}
        with self._lock:
            for d in self._per_policy.values():
                for p in self.PATHS:
                    out[p] += d[p]
        return out

    def confirm_rate(self) -> float:
        t = self.totals()
        denom = t["device"] + t["confirm"]
        return round(t["confirm"] / denom, 6) if denom else 0.0

    def state(self) -> Dict[str, Any]:
        return {"totals": self.totals(),
                "confirm_rate": self.confirm_rate(),
                "per_policy": self.per_policy()}

    def reset(self) -> None:
        with self._lock:
            self._per_policy.clear()


global_pattern_cells = PatternCellTracker()


# ---------------------------------------------------------------------------
# cardinality-bounded Prometheus exposition of the rule stats

DEFAULT_RULE_METRICS_TOPK = 20
OVERFLOW_POLICY = "_overflow"


def _env_topk() -> int:
    try:
        return int(os.environ.get("KYVERNO_TPU_RULE_METRICS_TOPK", "")
                   or DEFAULT_RULE_METRICS_TOPK)
    except ValueError:
        return DEFAULT_RULE_METRICS_TOPK


class RuleStatsCollector:
    """Pseudo-instrument rendered at scrape time: per-policy
    ``kyverno_rule_*`` families bounded to K policies; everything else
    collapses into one ``policy="_overflow"`` series — label
    cardinality stays O(K) no matter how many policies churn through
    the process.

    Membership is STICKY: once a policy earns a named series it keeps
    it, and a policy folded into the overflow bucket stays there (until
    the accumulator resets). Counter semantics demand this — if
    membership re-ranked per scrape, a policy crossing the K boundary
    would make both its own series and the overflow series DECREASE,
    which Prometheus reads as a counter reset and turns into spurious
    rate() spikes on exactly the families built for alerting."""

    def __init__(self, accumulator: Optional[RuleStatsAccumulator] = None,
                 top_k: Optional[int] = None):
        self.accumulator = accumulator
        self.top_k = top_k if top_k is not None else _env_topk()
        self._named: set = set()
        self._overflowed: set = set()

    def _acc(self) -> RuleStatsAccumulator:
        return self.accumulator if self.accumulator is not None \
            else global_rule_stats

    def _partition(self, aggs: List[Dict[str, Any]], k: int
                   ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
        """Split into (named, overflow) with sticky membership; free
        named slots go to the highest-volume undecided policies. An
        accumulator reset (fewer policies than we remember) clears the
        memory so tests and restarts start fresh."""
        seen = {a["policy"] for a in aggs}
        if not (self._named | self._overflowed) <= seen:
            self._named = set()
            self._overflowed = set()
        keep, over, undecided = [], [], []
        for a in aggs:  # aggs arrive sorted by eval volume
            if a["policy"] in self._named:
                keep.append(a)
            elif a["policy"] in self._overflowed:
                over.append(a)
            else:
                undecided.append(a)
        for a in undecided:
            if len(keep) < k:
                keep.append(a)
                self._named.add(a["policy"])
            else:
                over.append(a)
                self._overflowed.add(a["policy"])
        return keep, over

    def collect(self) -> List[str]:
        from .metrics import _fmt_labels, _labels_key

        aggs = self._acc().policy_aggregates()
        k = max(int(self.top_k), 0)
        keep, over = self._partition(aggs, k)
        if over:
            folded = {"policy": OVERFLOW_POLICY, "rules": 0,
                      "device_rules": 0, "evals": 0, "fired": 0,
                      "fails": 0, "never_fired": 0}
            for a in over:
                for key in ("rules", "device_rules", "evals", "fired",
                            "fails", "never_fired"):
                    folded[key] += a[key]
            folded["device_coverage"] = round(
                folded["device_rules"] / folded["rules"], 4) \
                if folded["rules"] else 0.0
            keep = keep + [folded]
        fams = (
            ("kyverno_rule_evals_total", "counter",
             "rule evaluations (all verdict classes) by policy", "evals"),
            ("kyverno_rule_fired_total", "counter",
             "rule firings (pass/fail/error verdicts) by policy", "fired"),
            ("kyverno_rule_fail_total", "counter",
             "rule FAIL verdicts by policy", "fails"),
            ("kyverno_rule_never_fired", "gauge",
             "rules that have never fired, by policy", "never_fired"),
            ("kyverno_policy_device_coverage", "gauge",
             "fraction of a policy's rules lowered onto the device",
             "device_coverage"),
        )
        out: List[str] = []
        for name, kind, help_, field in fams:
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {kind}")
            for a in sorted(keep, key=lambda a: a["policy"]):
                labels = _fmt_labels(_labels_key({"policy": a["policy"]}))
                out.append(f"{name}{labels} {float(a[field])}")
        return out


# ---------------------------------------------------------------------------
# device feed-starvation accounting

class StarvationTracker:
    """Rolling-window accounting of device busy vs encode-starved time.
    ``record`` is fed from the serial scan ladder and the pipelined
    scanner per chunk; the gauge is the continuously-updated fraction
    of device-relevant wall time spent waiting on host encode."""

    def __init__(self, window_s: float = 300.0, metrics=None,
                 clock=time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        # (t, busy_s, starved_s) events inside the rolling window, plus
        # running window sums maintained incrementally — record() sits
        # on the per-flush/per-chunk hot path and must not re-walk the
        # whole window per call
        self._events: deque = deque()   # guarded-by: _lock
        self._win_busy = 0.0            # guarded-by: _lock
        self._win_starved = 0.0         # guarded-by: _lock
        self._totals = {"device_busy": 0.0, "encode_wait": 0.0,  # guarded-by: _lock
                        "readback": 0.0, "host_assemble": 0.0}
        self._hooked = False

    def _registry(self):
        if self._metrics is None:
            from .metrics import global_registry

            self._metrics = global_registry
        if not self._hooked:
            self._hooked = True
            try:
                # the ratio decays as the window slides: refresh the
                # gauge at scrape time, not only at record time
                self._metrics.add_collect_hook(self.update_gauge)
            except Exception:
                pass
        return self._metrics

    def _evict_locked(self, now: float) -> None:
        while self._events and self._events[0][0] < now - self.window_s:
            _, busy, starved = self._events.popleft()
            self._win_busy -= busy
            self._win_starved -= starved

    def record(self, busy_s: float = 0.0, starved_s: float = 0.0,
               readback_s: float = 0.0, assemble_s: float = 0.0) -> None:
        now = self._clock()
        with self._lock:
            if busy_s or starved_s:
                self._events.append((now, busy_s, starved_s))
                self._win_busy += busy_s
                self._win_starved += starved_s
            self._evict_locked(now)
            self._totals["device_busy"] += busy_s
            self._totals["encode_wait"] += starved_s
            self._totals["readback"] += readback_s
            self._totals["host_assemble"] += assemble_s
        self.update_gauge()

    def ratio(self, now: Optional[float] = None) -> float:
        """starved / (busy + starved) over the rolling window, in
        [0, 1]; 0.0 with no samples."""
        now = self._clock() if now is None else now
        with self._lock:
            self._evict_locked(now)
            busy, starved = self._win_busy, self._win_starved
        denom = busy + starved
        return round(min(1.0, max(0.0, starved) / denom), 4) \
            if denom > 0 else 0.0

    def update_gauge(self) -> None:
        try:
            self._registry().feed_starvation.set(self.ratio())
        except Exception:
            pass

    def state(self) -> Dict[str, Any]:
        with self._lock:
            totals = {k: round(v, 6) for k, v in self._totals.items()}
            samples = len(self._events)
        return {"ratio": self.ratio(), "window_s": self.window_s,
                "samples": samples, "seconds_total": totals}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._win_busy = 0.0
            self._win_starved = 0.0
            for k in self._totals:
                self._totals[k] = 0.0


global_starvation = StarvationTracker()


# ---------------------------------------------------------------------------
# SLO burn-rate tracking

class SloConfig:
    """Targets; mutable so `serve` flags can tune the process-global
    tracker before traffic starts."""

    def __init__(self,
                 admission_p99_target_ms: float = 50.0,
                 admission_error_budget: float = 0.01,
                 scan_freshness_target_s: float = 300.0,
                 device_coverage_floor: float = 0.9,
                 windows: Optional[Dict[str, float]] = None):
        self.admission_p99_target_ms = admission_p99_target_ms
        self.admission_error_budget = admission_error_budget
        self.scan_freshness_target_s = scan_freshness_target_s
        self.device_coverage_floor = device_coverage_floor
        # multi-rate: a short window catches fast burns, a long window
        # catches slow leaks (the classic SRE pairing)
        self.windows = dict(windows) if windows else {"5m": 300.0,
                                                      "1h": 3600.0}


class SloTracker:
    """Rolling-window, multi-rate burn-rate tracking for the serving
    SLOs. Burn rate 1.0 = consuming exactly the error budget; >1 means
    the budget runs out before the window does."""

    def __init__(self, config: Optional[SloConfig] = None, metrics=None,
                 clock=time.monotonic, max_samples: int = 65536):
        self.config = config or SloConfig()
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        # (t, latency_s, class) — class is the scheduling priority tier
        # (serving/scheduler.py), "default" for unclassified callers,
        # so the windows split per class without unbounded cardinality
        self._adm: deque = deque(maxlen=max_samples)  # guarded-by: _lock
        # burn-rate cache for the serving shed ladder: submit() reads
        # the burn signal per request, so the read must not walk the
        # whole sample window each time
        self._burn_cache: Tuple[float, float] = (-1e9, 0.0)
        self._last_scan: Optional[float] = None  # guarded-by: _lock
        self._coverage: Optional[float] = None   # guarded-by: _lock
        # verdict-integrity samples: (t, diverged 0/1) per shadow-
        # verification check (observability/verification.py)
        self._verif: deque = deque(maxlen=max_samples)  # guarded-by: _lock
        # lifetime totals for the fleet telemetry plane: unlike the
        # bounded sample windows above these are true monotonic
        # counters, so the leader can merge cross-replica DELTAS
        # (fleet/telemetry.py) without window-alignment drift
        self._totals: Dict[str, int] = {           # guarded-by: _lock
            "admission_requests": 0, "admission_slow": 0, "scan_ticks": 0}
        self._hooked = False

    def _registry(self):
        if self._metrics is None:
            from .metrics import global_registry

            self._metrics = global_registry
        if not self._hooked:
            self._hooked = True
            try:
                self._metrics.add_collect_hook(self.update_gauges)
            except Exception:
                pass
        return self._metrics

    # -- write side

    def record_admission(self, latency_s: float,
                         cls: Optional[str] = None) -> None:
        slow = latency_s > self.config.admission_p99_target_ms / 1000.0
        with self._lock:
            self._adm.append((self._clock(), latency_s, cls or "default"))
            self._totals["admission_requests"] += 1
            if slow:
                self._totals["admission_slow"] += 1

    def admission_burn_fast(self, max_age_s: float = 0.25) -> float:
        """Cached short-window admission burn rate — the signal the
        serving pipeline's burn-driven shed ladder reads per submit().
        Recomputed at most every ``max_age_s``; between refreshes the
        ladder sees a trailing value, which is fine — burn is a
        windowed rate, not an instantaneous one."""
        now = self._clock()
        cached_at, cached = self._burn_cache
        if now - cached_at < max_age_s:
            return cached
        cfg = self.config
        span = min(cfg.windows.values()) if cfg.windows else 300.0
        target_s = cfg.admission_p99_target_ms / 1000.0
        budget = max(cfg.admission_error_budget, 1e-9)
        cutoff = now - span
        n = slow = 0
        with self._lock:
            for t, l, _c in reversed(self._adm):
                if t < cutoff:
                    break
                n += 1
                if l > target_s:
                    slow += 1
        burn = (slow / n) / budget if n else 0.0
        self._burn_cache = (now, burn)
        return burn

    def record_scan(self, coverage: Optional[float] = None,
                    lag_s: float = 0.0) -> None:
        """A scan tick completed. ``lag_s`` sets the freshness clock
        BACK: under a fleet the completed tick may still be serving
        shards whose last real scan happened on a now-dead replica —
        the scan-freshness SLO must age from the oldest owned shard,
        not from the tick that merely took ownership."""
        with self._lock:
            self._last_scan = self._clock() - max(lag_s, 0.0)
            if coverage is not None:
                self._coverage = coverage
            self._totals["scan_ticks"] += 1
        self.update_gauges()

    def set_device_coverage(self, coverage: float) -> None:
        with self._lock:
            self._coverage = coverage
        self.update_gauges()

    def record_verification(self, diverged: bool) -> None:
        """One shadow-verification check: the verdict-integrity SLO's
        error budget is ZERO divergences — any diverged sample in a
        window marks the SLO breached for that window's span."""
        with self._lock:
            self._verif.append((self._clock(), 1 if diverged else 0))
        if diverged:
            self.update_gauges()

    def reset(self) -> None:
        with self._lock:
            self._adm.clear()
            self._last_scan = None
            self._coverage = None
            self._verif.clear()
            self._burn_cache = (-1e9, 0.0)
            self._totals = {"admission_requests": 0, "admission_slow": 0,
                            "scan_ticks": 0}

    # -- fleet telemetry feed (fleet/telemetry.py)

    def telemetry_counters(self) -> Dict[str, int]:
        """Lifetime monotonic totals — the delta-mergeable half of a
        replica's telemetry snapshot."""
        with self._lock:
            return dict(self._totals)

    def telemetry_windows(self, now: Optional[float] = None
                          ) -> Dict[str, Dict[str, int]]:
        """Per-window raw admission/divergence sample counts. These are
        the numbers the leader SUMS across replicas to recompute the
        fleet burn — shipping counts instead of each replica's own burn
        ratio keeps the fleet rollup a weighted merge, not an average
        of averages."""
        now = self._clock() if now is None else now
        target_s = self.config.admission_p99_target_ms / 1000.0
        with self._lock:
            adm = list(self._adm)
            verif = list(self._verif)
        out: Dict[str, Dict[str, int]] = {}
        for name, span in self.config.windows.items():
            lat = [l for (t, l, _c) in adm if t >= now - span]
            out[name] = {
                "requests": len(lat),
                "slow": sum(1 for l in lat if l > target_s),
                "divergences": sum(d for (t, d) in verif
                                   if t >= now - span),
            }
        return out

    # -- read side

    @staticmethod
    def _window_stats(lat: List[float], target_s: float,
                      budget: float) -> Dict[str, Any]:
        n = len(lat)
        slow = sum(1 for l in lat if l > target_s)
        p99 = float(np.percentile(np.asarray(lat), 99)) if lat else 0.0
        burn = (slow / n) / budget if n else 0.0
        return {"requests": n, "slow": slow,
                "p99_ms": round(p99 * 1e3, 3),
                "burn_rate": round(burn, 4)}

    def _admission_windows(self, now: float) -> Dict[str, Dict[str, Any]]:
        cfg = self.config
        target_s = cfg.admission_p99_target_ms / 1000.0
        budget = max(cfg.admission_error_budget, 1e-9)
        with self._lock:
            samples = list(self._adm)
        out: Dict[str, Dict[str, Any]] = {}
        for name, span in cfg.windows.items():
            win = [(l, c) for (t, l, c) in samples if t >= now - span]
            w = self._window_stats([l for l, _ in win], target_s, budget)
            # per-class split (serving scheduling classes): the shed
            # ladder degrades bulk first, and these windows are how an
            # operator verifies the critical class really stayed flat
            by_class: Dict[str, Dict[str, Any]] = {}
            for c in sorted({c for _, c in win}):
                by_class[c] = self._window_stats(
                    [l for l, cc in win if cc == c], target_s, budget)
            w["by_class"] = by_class
            out[name] = w
        return out

    def _verification_windows(self, now: float) -> Dict[str, Dict[str, int]]:
        with self._lock:
            samples = list(self._verif)
        out: Dict[str, Dict[str, int]] = {}
        for name, span in self.config.windows.items():
            win = [d for (t, d) in samples if t >= now - span]
            out[name] = {"checked": len(win), "divergences": sum(win)}
        return out

    def state(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else now
        cfg = self.config
        adm = self._admission_windows(now)
        verif = self._verification_windows(now)
        with self._lock:
            last_scan, coverage = self._last_scan, self._coverage
        freshness = (now - last_scan) if last_scan is not None else None
        fresh_burn = (freshness / max(cfg.scan_freshness_target_s, 1e-9)
                      if freshness is not None else 0.0)
        cov_ok = coverage is None or coverage >= cfg.device_coverage_floor
        breached = []
        if any(w["burn_rate"] > 1.0 for w in adm.values()):
            breached.append("admission_latency")
        if freshness is not None and fresh_burn > 1.0:
            breached.append("scan_freshness")
        if not cov_ok:
            breached.append("device_coverage")
        if any(w["divergences"] for w in verif.values()):
            # error budget zero: verdicts diverging from the oracle is
            # never acceptable spend
            breached.append("verdict_integrity")
        return {
            "verdict_integrity": {
                "windows": verif,
                "ok": "verdict_integrity" not in breached,
            },
            "admission": {
                "target_p99_ms": cfg.admission_p99_target_ms,
                "error_budget": cfg.admission_error_budget,
                "windows": adm,
            },
            "scan_freshness": {
                "seconds_since_scan": (round(freshness, 3)
                                       if freshness is not None else None),
                "target_s": cfg.scan_freshness_target_s,
                "burn_rate": round(fresh_burn, 4),
            },
            "device_coverage": {
                "ratio": coverage,
                "floor": cfg.device_coverage_floor,
                "ok": cov_ok,
            },
            "breached": breached,
        }

    def update_gauges(self) -> None:
        try:
            reg = self._registry()
            state = self.state()
            self._notify_burns(state["breached"])
            for name, w in state["admission"]["windows"].items():
                reg.slo_admission_p99.set(w["p99_ms"] / 1e3,
                                          {"window": name})
                reg.slo_admission_burn.set(w["burn_rate"], {"window": name})
                for cls, cw in w.get("by_class", {}).items():
                    reg.slo_admission_p99.set(
                        cw["p99_ms"] / 1e3, {"window": name, "class": cls})
                    reg.slo_admission_burn.set(
                        cw["burn_rate"], {"window": name, "class": cls})
            fresh = state["scan_freshness"]
            if fresh["seconds_since_scan"] is not None:
                reg.slo_scan_freshness.set(fresh["seconds_since_scan"])
                reg.slo_scan_freshness_burn.set(fresh["burn_rate"])
            cov = state["device_coverage"]["ratio"]
            if cov is not None:
                reg.slo_device_coverage.set(cov)
            for name, w in state["verdict_integrity"]["windows"].items():
                reg.slo_verification_divergences.set(
                    float(w["divergences"]), {"window": name})
            for slo in ("admission_latency", "scan_freshness",
                        "device_coverage", "verdict_integrity"):
                reg.slo_breached.set(
                    1.0 if slo in state["breached"] else 0.0, {"slo": slo})
        except Exception:
            pass  # SLO bookkeeping must never break a scrape or request

    def _notify_burns(self, breached) -> None:
        """A NEWLY burning SLO is an incident moment: spool the flight
        ring (the last N decisions are the evidence) and emit one
        structured log event. Repeats while the same SLO keeps burning
        stay quiet — the gauges carry the ongoing state."""
        prev = getattr(self, "_last_breached", frozenset())
        cur = frozenset(breached)
        self._last_breached = cur
        new = cur - prev
        if not new:
            return
        try:
            from .flightrecorder import global_flight

            global_flight.on_slo_burn(sorted(new))
        except Exception:
            pass
        try:
            from .log import global_oplog

            global_oplog.emit("slo_burn", level="warn",
                              slos=sorted(new), all_breached=sorted(cur))
        except Exception:
            pass


global_slo = SloTracker()
