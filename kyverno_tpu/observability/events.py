"""Event generator — async policy-event emission.

Mirror of pkg/event/controller.go:34: events enqueue without blocking
the admission/scan path, worker threads drain the queue to a pluggable
sink (in-cluster this would be the Events API; offline it is a log or
callback), the queue drops on overflow (maxQueuedEvents), and reasons
can be omitted (omit-list, cmd/kyverno/main.go:354).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

REASON_POLICY_VIOLATION = "PolicyViolation"
REASON_POLICY_APPLIED = "PolicyApplied"
REASON_POLICY_ERROR = "PolicyError"
REASON_POLICY_SKIPPED = "PolicySkipped"


@dataclass
class Event:
    reason: str
    message: str
    policy: str = ""
    rule: str = ""
    resource_kind: str = ""
    resource_name: str = ""
    resource_namespace: str = ""
    type: str = "Warning"  # Warning | Normal
    related: Dict[str, Any] = field(default_factory=dict)


class EventGenerator:
    def __init__(
        self,
        sink: Optional[Callable[[Event], None]] = None,
        workers: int = 3,
        max_queued: int = 1000,
        omit_reasons: Optional[List[str]] = None,
        metrics=None,
    ) -> None:
        self._sink = sink or (lambda e: None)
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=max_queued)
        self._omit = set(omit_reasons or [])
        # every counter mutation holds _counter_lock — add() and the
        # worker threads race on these, and a lost drop increment hides
        # an overload signal
        self.dropped = 0   # guarded-by: _counter_lock
        self.emitted = 0   # guarded-by: _counter_lock
        self._counter_lock = threading.Lock()
        if metrics is None:
            from .metrics import global_registry

            metrics = global_registry
        self.metrics = metrics
        self._workers = [
            threading.Thread(target=self._drain, daemon=True) for _ in range(workers)
        ]
        self._started = False
        self._stopping = False
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            if not self._started:
                for w in self._workers:
                    w.start()
                self._started = True

    def add(self, *events: Event) -> None:
        """Non-blocking enqueue; drops on overflow (the reference logs
        and drops rather than back-pressuring admission)."""
        self.start()
        for e in events:
            if e.reason in self._omit:
                continue
            try:
                self._queue.put_nowait(e)
            except queue.Full:
                with self._counter_lock:
                    self.dropped += 1
                self.metrics.events_dropped.inc()

    def _drain(self) -> None:
        while True:
            e = self._queue.get()
            if e is None:
                self._queue.task_done()
                return
            try:
                self._sink(e)
                with self._counter_lock:
                    self.emitted += 1
                self.metrics.events_emitted.inc()
            except Exception:
                pass
            finally:
                self._queue.task_done()

    def flush(self, timeout: float = 5.0) -> None:
        """Wait until every queued event has been fully processed
        (task_done accounting covers sink calls in flight)."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._queue.all_tasks_done:
                if self._queue.unfinished_tasks == 0:
                    return
            time.sleep(0.005)

    def stop(self, timeout: float = 5.0) -> None:
        """Stop workers and JOIN them within a bound: a sentinel that
        cannot be enqueued now (queue full) is retried as workers drain,
        and a worker wedged in a stuck sink is abandoned at the deadline
        (daemon threads) rather than hanging shutdown forever."""
        import time

        with self._lock:
            if not self._started or self._stopping:
                return
            self._stopping = True
        deadline = time.time() + timeout
        pending = len(self._workers)
        while pending and time.time() < deadline:
            try:
                self._queue.put(None, timeout=0.05)
                pending -= 1
            except queue.Full:
                continue
        for w in self._workers:
            w.join(timeout=max(0.0, deadline - time.time()))
