"""Flight recorder — a bounded black box over the admission/scan ladder.

Every rung of the dispatch ladder (device, breaker-OPEN scalar,
quarantine, pipelined, pooled-encode, cached replay, DFA-confirm)
claims bit-identical verdicts, but a running deployment recorded
nothing about what was actually decided. This module keeps a bounded
in-memory ring of per-decision records — the evaluated resource body
(by reference; serialized and size-capped only at dump/spool time),
its content sha, the policy-set revision + content key, the dispatch
path and breaker state, the full verdict column, the trace id, and
phase timings — with head-based sampling:

- outcomes in ``ALWAYS_CAPTURE`` (error / scalar fallback / pattern
  CONFIRM / shed / expired / hedged race) are captured unconditionally
  — the rare paths are exactly the ones an incident needs;
- everything else (ok, cached) is captured at ``sample_rate`` (the
  ``serve --flight-sample-rate`` knob, default 1%), so the recorder's
  hot-path cost is one outcome classification + one RNG draw.

The ring dumps via ``/debug/flight?last=N`` and ``kyverno-tpu
flight-dump``, and spools to ``--flight-dir`` as newline-delimited
JSON automatically when a breaker transition or an SLO burn fires
(with a cooldown so a flapping breaker cannot flood the disk). Spooled
captures feed ``kyverno-tpu replay`` (offline re-evaluation + diff)
and the shadow verifier (observability/verification.py), which
replays sampled records through the scalar oracle at the pinned
revision and counts divergences.

Records hold a reference to the engine (compiled policy-set version)
that produced them so the verifier evaluates at the PINNED revision,
not whatever is active by the time the low-priority thread gets to it;
the reference is dropped from serialized output.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# outcomes captured regardless of the sample rate ("mutated" records
# are the shadow verifier's only evidence that a template-stamped patch
# matched the scalar oracle — they must never sample out)
ALWAYS_CAPTURE = frozenset({"error", "fallback", "shed", "confirm",
                            "expired", "hedged", "mutated"})

OUTCOME_OK = "ok"
OUTCOME_ERROR = "error"
OUTCOME_FALLBACK = "fallback"
OUTCOME_SHED = "shed"
OUTCOME_CONFIRM = "confirm"
OUTCOME_CACHED = "cached"
OUTCOME_EXPIRED = "expired"
# a hedged scalar dispatch raced an in-flight device batch; the record
# path names the winner ("hedged_scalar" / "hedged_device") and the
# race always captures — bit-identity under racing is exactly the
# claim the audit trail exists to witness
OUTCOME_HEDGED = "hedged"
# a batched-mutation decision: the record carries the patched body +
# its sha next to the original, and the verifier diffs the PATCHED
# output against a scalar re-patch (rows are routing, not verdicts)
OUTCOME_MUTATED = "mutated"

# verdict code mirror (tpu/evaluator.py order; this module must stay
# importable without jax, like the rest of observability/)
_ERROR_CODE = 4

_SPOOL_COOLDOWN_S = 5.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def policyset_key(engine: Any) -> str:
    """Content key of the compiled policy set an engine serves —
    memoized on the engine (cache_key() digests every policy)."""
    if engine is None:
        return ""
    key = getattr(engine, "_flight_ps_key", None)
    if key is None:
        try:
            key = engine.cps.cache_key()
        except Exception:
            key = ""
        try:
            engine._flight_ps_key = key
        except Exception:
            pass
    return key


def patched_digest(doc: Optional[Dict[str, Any]]) -> Optional[str]:
    """Content sha of a patched body — the SAME canonical hash the
    verdict cache keys resources with, so the webhook's recorded
    ``patched_sha`` and the verifier's scalar re-patch digest are
    directly comparable."""
    if doc is None:
        return None
    try:
        from ..tpu.cache import resource_content_hash

        return resource_content_hash(doc)
    except Exception:
        return None


def _replica_id() -> Optional[str]:
    """This process's fleet replica id (None outside a fleet) — the
    per-record tag that attributes spooled decisions to a failure
    domain."""
    try:
        from ..fleet.manager import current_replica_id

        return current_replica_id()
    except Exception:
        return None


class FlightRecord:
    """One recorded decision. Bodies and verdict rows are held by
    reference — building a record costs dict-slot assignments, never a
    serialization; the JSON shape materializes at to_dict() time."""

    __slots__ = ("kind", "seq", "ts", "trace_id", "outcome", "path",
                 "breaker", "revision", "ps_key", "resource",
                 "resource_sha", "namespace", "operation", "userinfo",
                 "ns_labels", "verdicts", "timings", "engine",
                 "patched", "patched_sha")

    def __init__(self, kind: str, outcome: str, path: str,
                 resource: Optional[Dict[str, Any]],
                 verdicts: Optional[List[Tuple[Tuple[str, str], int]]],
                 *, trace_id: str = "", breaker: str = "",
                 revision: Optional[int] = None, ps_key: str = "",
                 resource_sha: Optional[str] = None, namespace: str = "",
                 operation: str = "", userinfo: Optional[Dict] = None,
                 ns_labels: Optional[Dict[str, str]] = None,
                 timings: Optional[Dict[str, float]] = None,
                 engine: Any = None, ts: Optional[float] = None,
                 seq: int = 0, patched: Optional[Dict[str, Any]] = None,
                 patched_sha: Optional[str] = None):
        self.kind = kind
        self.seq = seq
        self.ts = time.time() if ts is None else ts
        self.trace_id = trace_id or ""
        self.outcome = outcome
        self.path = path
        self.breaker = breaker
        self.revision = revision
        self.ps_key = ps_key
        self.resource = resource
        self.resource_sha = resource_sha
        self.namespace = namespace
        self.operation = operation
        self.userinfo = userinfo
        self.ns_labels = ns_labels
        self.verdicts = verdicts
        self.timings = timings
        self.engine = engine
        self.patched = patched
        self.patched_sha = patched_sha

    def to_dict(self, body_cap: Optional[int] = None) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "kind": self.kind, "seq": self.seq,
            "ts": round(self.ts, 3), "trace_id": self.trace_id,
            "outcome": self.outcome, "path": self.path,
            "breaker": self.breaker,
            "policyset_revision": self.revision,
            "policyset_key": self.ps_key,
            "resource_sha": self.resource_sha,
            "namespace": self.namespace, "operation": self.operation,
        }
        # fleet: records are tagged with the replica that made the
        # decision, so a spooled capture from a 3-replica incident
        # says WHICH failure domain each verdict came from
        replica = _replica_id()
        if replica:
            doc["replica"] = replica
        if self.userinfo:
            doc["userinfo"] = self.userinfo
        if self.ns_labels:
            doc["ns_labels"] = self.ns_labels
        if self.timings:
            doc["timings"] = {k: round(v, 6)
                              for k, v in self.timings.items()}
        if self.verdicts is not None:
            doc["verdicts"] = [[p, r, int(c)]
                               for (p, r), c in self.verdicts]
        body = self.resource
        if body is not None:
            try:
                blob = json.dumps(body, sort_keys=True,
                                  separators=(",", ":"))
            except (TypeError, ValueError):
                blob = None
            cap = self._body_cap() if body_cap is None else body_cap
            if blob is not None and len(blob) <= cap:
                doc["resource"] = body
                doc["resource_bytes"] = len(blob)
            else:
                # the sha still identifies the body; replay/verify skip
                doc["resource"] = None
                doc["resource_truncated"] = True
                if blob is not None:
                    doc["resource_bytes"] = len(blob)
        if self.patched_sha is not None:
            doc["patched_sha"] = self.patched_sha
        if self.patched is not None:
            try:
                blob = json.dumps(self.patched, sort_keys=True,
                                  separators=(",", ":"))
            except (TypeError, ValueError):
                blob = None
            cap = self._body_cap() if body_cap is None else body_cap
            if blob is not None and len(blob) <= cap:
                doc["patched"] = self.patched
            else:
                doc["patched"] = None
                doc["patched_truncated"] = True
        return doc

    @staticmethod
    def _body_cap() -> int:
        return global_flight.body_cap


class FlightRecorder:
    """Process-wide bounded ring + spool of FlightRecords."""

    def __init__(self, capacity: Optional[int] = None,
                 sample_rate: Optional[float] = None,
                 spool_dir: Optional[str] = None, metrics=None,
                 clock=time.monotonic):
        self._default_capacity = (
            capacity if capacity is not None
            else _env_int("KYVERNO_TPU_FLIGHT_CAPACITY", 2048))
        self._default_sample = (
            sample_rate if sample_rate is not None
            else _env_float("KYVERNO_TPU_FLIGHT_SAMPLE", 0.01))
        self._default_body_cap = _env_int("KYVERNO_TPU_FLIGHT_BODY_CAP",
                                          65536)
        # spool bounds: a soak that spools for hours must not grow the
        # disk without limit — keep the newest N flight-*.ndjson
        # segments, and rotate divergences.ndjson through N size-capped
        # segments (dropped segments are counted, never silent)
        self._default_spool_segments = _env_int(
            "KYVERNO_TPU_FLIGHT_SPOOL_SEGMENTS", 32)
        self._default_divergence_bytes = _env_int(
            "KYVERNO_TPU_FLIGHT_DIVERGENCE_MAX_BYTES", 16 << 20)
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        self._rng = random.Random()
        self._sinks: List[Callable[[FlightRecord], None]] = []
        with self._lock:
            self._reset_state_locked()

    def _reset_state_locked(self) -> None:
        self.capacity = self._default_capacity
        self.sample_rate = self._default_sample
        self.body_cap = self._default_body_cap
        self.spool_dir: Optional[str] = None
        self.max_spool_segments = self._default_spool_segments
        self.divergence_max_bytes = self._default_divergence_bytes
        self._ring: deque = deque(maxlen=max(1, self.capacity))  # guarded-by: _lock
        self._seq = 0            # guarded-by: _lock
        self._last_spool_at = -1e9   # guarded-by: _lock
        self._spool_seq = 0          # guarded-by: _lock
        # guarded-by: _lock
        self.stats: Dict[str, Any] = {
            "captured": 0, "sampled_out": 0, "spools": 0,
            "by_outcome": {}, "divergences_spooled": 0,
            "spool_segments_dropped": 0,
            "divergence_segments_dropped": 0}

    # -- configuration

    def configure(self, capacity: Optional[int] = None,
                  sample_rate: Optional[float] = None,
                  spool_dir: Optional[str] = None,
                  body_cap: Optional[int] = None,
                  max_spool_segments: Optional[int] = None,
                  divergence_max_bytes: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and capacity != self.capacity:
                self.capacity = max(1, capacity)
                self._ring = deque(self._ring, maxlen=self.capacity)
            if sample_rate is not None:
                self.sample_rate = min(1.0, max(0.0, sample_rate))
            if spool_dir is not None:
                self.spool_dir = spool_dir or None
            if body_cap is not None:
                self.body_cap = body_cap
            if max_spool_segments is not None:
                self.max_spool_segments = max(0, max_spool_segments)
            if divergence_max_bytes is not None:
                self.divergence_max_bytes = max(0, divergence_max_bytes)

    def reset(self) -> None:
        """Back to construction defaults (per-test isolation)."""
        with self._lock:
            self._reset_state_locked()
        self._sinks = []

    def add_sink(self, fn: Callable[[FlightRecord], None]) -> None:
        """Post-capture hook (the shadow verifier registers here): runs
        for every CAPTURED record, outside the ring lock."""
        if fn not in self._sinks:
            self._sinks.append(fn)

    @property
    def enabled(self) -> bool:
        """The recorder is always on (the ring is cheap); `enabled` is
        the short-circuit for callers that build record *inputs*: with
        rate 0 only ALWAYS_CAPTURE outcomes land, which still needs the
        inputs — so this is True unless capacity is zeroed."""
        return self.capacity > 0

    def _registry(self):
        if self._metrics is None:
            from .metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    # -- capture

    @staticmethod
    def classify(rows: Optional[Sequence[Tuple[Tuple[str, str], int]]],
                 path: str, error: Optional[BaseException] = None,
                 confirm: bool = False, mutated: bool = False) -> str:
        """Outcome classification, most-interesting-wins: error >
        shed/expired > mutated > hedged > fallback > confirm > cached >
        ok. ``mutated`` outranks the path-derived classes so every
        successful mutate decision — including ``hedged_mutate`` and
        ``cached_mutate`` paths — lands in the mutate outcome class the
        verifier's patched-output diff selects on (the path string
        still says HOW it resolved)."""
        if error is not None:
            from ..serving.queue import DeadlineExceededError

            return (OUTCOME_EXPIRED
                    if isinstance(error, DeadlineExceededError)
                    else OUTCOME_ERROR)
        if rows is not None and any(c == _ERROR_CODE for _, c in rows):
            return OUTCOME_ERROR
        if path == "shed":
            return OUTCOME_SHED
        if mutated:
            return OUTCOME_MUTATED
        if path.startswith("hedged"):
            return OUTCOME_HEDGED
        if path in ("scalar_fallback", "pure_scalar"):
            return OUTCOME_FALLBACK
        if confirm:
            return OUTCOME_CONFIRM
        if path == "cached":
            return OUTCOME_CACHED
        return OUTCOME_OK

    def should_capture(self, outcome: str) -> bool:
        if self.capacity <= 0:
            return False
        if outcome in ALWAYS_CAPTURE:
            return True
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0 or self._rng.random() >= self.sample_rate:
            with self._lock:
                self.stats["sampled_out"] += 1
            try:
                self._registry().flight_sampled_out.inc()
            except Exception:
                pass
            return False
        return True

    def record(self, rec: FlightRecord) -> Optional[FlightRecord]:
        """Append one already-built record (sampling must have been
        decided via should_capture — record() always captures)."""
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._ring.append(rec)
            self.stats["captured"] += 1
            by = self.stats["by_outcome"]
            by[rec.outcome] = by.get(rec.outcome, 0) + 1
            ring_n = len(self._ring)
        try:
            reg = self._registry()
            reg.flight_records.inc({"outcome": rec.outcome})
            reg.flight_ring_size.set(ring_n)
        except Exception:
            pass
        for sink in self._sinks:
            try:
                sink(rec)
            except Exception:
                pass
        # the engine reference exists for the shadow verifier, which
        # has now either verified the record (synchronous), enqueued
        # its own strong reference (async), or declined it. The RING
        # must not pin superseded compiled versions in memory until
        # 2048 records turn over — under policy churn that is every
        # dead engine ever recorded
        rec.engine = None
        return rec

    def record_admission(self, resource: Optional[Dict[str, Any]],
                         rows: Optional[List[Tuple[Tuple[str, str], int]]],
                         path: str, *, error: Optional[BaseException] = None,
                         engine: Any = None,
                         revision: Optional[int] = None,
                         namespace: str = "", operation: str = "",
                         userinfo: Optional[Dict] = None,
                         ns_labels: Optional[Dict[str, str]] = None,
                         trace_id: str = "",
                         timings: Optional[Dict[str, float]] = None,
                         confirm: bool = False,
                         kind: str = "admission",
                         outcome: Optional[str] = None,
                         patched: Optional[Dict[str, Any]] = None,
                         patched_sha: Optional[str] = None
                         ) -> Optional[FlightRecord]:
        """Classify + sample + build + append one admission (or scan)
        record. All the potentially-expensive derivations (sha, policy-
        set key, breaker state) happen only after the sampling
        decision. A caller that already gated on classify() +
        should_capture() (to keep ITS expensive inputs behind the gate
        too) passes the decided ``outcome`` — sampling is not re-run."""
        if outcome is None:
            outcome = self.classify(rows, path, error=error,
                                    confirm=confirm,
                                    mutated=kind == "mutate")
            if not self.should_capture(outcome):
                return None
        # every mutate capture path must label its records: a mutate
        # record classified into a validate-shaped class (ok/cached/
        # fallback/...) would silently fall out of the verifier's
        # patched-output diff. Failure classes are the only exceptions
        # — there is no patched output to diff.
        assert kind != "mutate" or outcome in (
            OUTCOME_MUTATED, OUTCOME_ERROR, OUTCOME_EXPIRED,
            OUTCOME_SHED), f"unlabeled mutate record: {outcome!r}"
        sha = None
        if resource is not None:
            try:
                from ..tpu.cache import resource_content_hash

                sha = resource_content_hash(resource)
            except Exception:
                sha = None
        try:
            from ..resilience.breaker import tpu_breaker

            breaker = tpu_breaker().state
        except Exception:
            breaker = ""
        if patched is not None and patched_sha is None:
            patched_sha = patched_digest(patched)
        rec = FlightRecord(
            kind=kind, outcome=outcome, path=path, resource=resource,
            verdicts=list(rows) if rows is not None else None,
            trace_id=trace_id, breaker=breaker, revision=revision,
            ps_key=policyset_key(engine), resource_sha=sha,
            namespace=namespace, operation=operation, userinfo=userinfo,
            ns_labels=ns_labels, timings=timings, engine=engine,
            patched=patched, patched_sha=patched_sha)
        return self.record(rec)

    def record_scan_chunk(self, chunk, result, engine: Any = None,
                          ns_labels: Optional[Dict[str, Dict[str, str]]]
                          = None, revision: Optional[int] = None,
                          path: str = "scan", fallback: bool = False,
                          confirm: bool = False) -> int:
        """Per-resource sampled records for one evaluated (or cache-
        served) scan chunk. ``chunk`` is the scanner's list of
        (uid, resource, sha) triples; the chunk's verdict table supplies
        one column per resource. ``fallback``/``confirm`` are chunk-
        level signals from the caller (dispatch-path thread-local,
        engine confirm flag): the always-capture contract covers the
        scan side too — a breaker-OPEN scan tick must land in the ring
        regardless of the sample rate. Returns records captured."""
        if self.capacity <= 0 or result is None:
            return 0
        if getattr(result, "infra_error", False):
            # ERROR fill-in rows (the scan ladder's escape hatch) are
            # served but are NOT content truth: the verifier comparing
            # them to the oracle would raise a false divergence alarm
            engine = None
        import numpy as np

        verdicts = np.asarray(result.verdicts)
        if verdicts.ndim != 2 or verdicts.shape[1] < len(chunk):
            return 0
        err_cols = (verdicts == _ERROR_CODE).any(axis=0)
        chunk_outcome = (OUTCOME_FALLBACK if fallback
                         else OUTCOME_CONFIRM if confirm else OUTCOME_OK)
        nsmap = ns_labels or {}
        # ONE breaker-state read per chunk: the state cannot usefully
        # change per resource, and the read takes the same lock the
        # admission dispatch path contends on
        try:
            from ..resilience.breaker import tpu_breaker

            breaker = tpu_breaker().state
        except Exception:
            breaker = ""
        captured = 0
        for ci, (uid, res, h) in enumerate(chunk):
            outcome = OUTCOME_ERROR if err_cols[ci] else chunk_outcome
            if not self.should_capture(outcome):
                continue
            meta = (res.get("metadata") or {}) if isinstance(res, dict) \
                else {}
            ns = (meta.get("name", "")
                  if isinstance(res, dict) and res.get("kind") == "Namespace"
                  else meta.get("namespace", ""))
            rows = list(zip(result.rules,
                            (int(c) for c in verdicts[:, ci])))
            self.record(FlightRecord(
                kind="scan", outcome=outcome, path=path, resource=res,
                verdicts=rows, breaker=breaker, revision=revision,
                ps_key=policyset_key(engine), resource_sha=h,
                namespace=ns, operation="",
                ns_labels=nsmap.get(ns, {}) or None, engine=engine))
            captured += 1
        return captured

    # -- read side

    def dump(self, last: int = 100) -> List[Dict[str, Any]]:
        with self._lock:
            # [-0:] would be the WHOLE ring, not zero records
            records = list(self._ring)[-last:] if last > 0 else []
        return [r.to_dict(self.body_cap) for r in records]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def state(self) -> Dict[str, Any]:
        with self._lock:
            stats = {k: (dict(v) if isinstance(v, dict) else v)
                     for k, v in self.stats.items()}
            ring_n = len(self._ring)
        return {"capacity": self.capacity,
                "sample_rate": self.sample_rate,
                "records": ring_n,
                "spool_dir": self.spool_dir,
                "body_cap": self.body_cap,
                "max_spool_segments": self.max_spool_segments,
                "divergence_max_bytes": self.divergence_max_bytes,
                "stats": stats}

    # -- spool

    def spool(self, reason: str = "manual", force: bool = False
              ) -> Optional[str]:
        """Write the current ring to the spool dir as NDJSON; returns
        the path, or None (no dir / cooldown). Auto-triggers (breaker
        transitions, SLO burns) respect a cooldown so a flapping
        breaker cannot flood the disk; explicit dumps force."""
        from ..resilience import storage as st

        spool_dir = self.spool_dir
        if not spool_dir:
            return None
        # degraded-storage ladder (surface flight_spool): while the
        # disk is sick, spools are counted drops — the in-memory ring
        # keeps recording, and a due re-probe lets one spool attempt
        # through to heal the surface
        if not st.storage_health(st.SURFACE_FLIGHT).allow():
            return None
        now = self._clock()
        with self._lock:
            if not force and now - self._last_spool_at < _SPOOL_COOLDOWN_S:
                return None
            self._last_spool_at = now
            self._spool_seq += 1
            seq = self._spool_seq
            records = list(self._ring)
            self.stats["spools"] += 1
        try:
            st.makedirs(spool_dir, st.SURFACE_FLIGHT)
            safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                           for c in reason)[:60] or "spool"
            path = os.path.join(
                spool_dir, f"flight-{int(time.time())}-{seq:04d}-"
                           f"{safe}.ndjson")
            # one frame per record: a write that dies mid-segment
            # leaves whole-line prefixes load_capture() can still read
            with st.open_truncate(path, st.SURFACE_FLIGHT) as fh:
                for rec in records:
                    st.write_frame(
                        fh,
                        json.dumps(rec.to_dict(self.body_cap),
                                   default=str) + "\n",
                        st.SURFACE_FLIGHT, path=path)
        except OSError:
            return None
        dropped = self._prune_spool_segments(spool_dir)
        if dropped:
            with self._lock:
                self.stats["spool_segments_dropped"] = \
                    self.stats.get("spool_segments_dropped", 0) + dropped
        try:
            self._registry().flight_spools.inc({"reason": safe})
        except Exception:
            pass
        try:
            from .log import global_oplog

            global_oplog.emit("flight_spool", reason=reason, path=path,
                              records=len(records))
        except Exception:
            pass
        return path

    def spool_divergence(self, record_doc: Dict[str, Any],
                         expected: List[Tuple[Tuple[str, str], int]],
                         got: List[Tuple[Tuple[str, str], int]]
                         ) -> Optional[str]:
        """Append one shadow-verification divergence (the full record +
        both verdict tables) to ``divergences.ndjson`` in the spool
        dir — no cooldown: every divergence is evidence."""
        from ..resilience import storage as st

        spool_dir = self.spool_dir
        if not spool_dir:
            return None
        # its own surface (``divergences``): divergence evidence and
        # routine flight spools degrade independently
        if not st.storage_health(st.SURFACE_DIVERGENCES).allow():
            return None
        doc = {"kind": "divergence", "ts": round(time.time(), 3),
               "record": record_doc,
               "expected": [[p, r, int(c)] for (p, r), c in expected],
               "got": [[p, r, int(c)] for (p, r), c in got]}
        try:
            st.makedirs(spool_dir, st.SURFACE_DIVERGENCES)
            path = os.path.join(spool_dir, "divergences.ndjson")
            dropped = self._rotate_divergences(path)
            with self._lock:
                self.stats["divergences_spooled"] += 1
                if dropped:
                    self.stats["divergence_segments_dropped"] = \
                        self.stats.get("divergence_segments_dropped", 0) \
                        + dropped
            with st.open_append(path, st.SURFACE_DIVERGENCES) as fh:
                st.write_frame(fh, json.dumps(doc, default=str) + "\n",
                               st.SURFACE_DIVERGENCES, path=path)
        except OSError:
            return None
        return path

    # -- spool bounds (a soak must not grow the disk without limit)

    def _prune_spool_segments(self, spool_dir: str) -> int:
        """Keep only the newest ``max_spool_segments`` flight-*.ndjson
        files (names sort chronologically: epoch + spool seq). Returns
        how many segments were dropped; 0 disables the cap."""
        keep = self.max_spool_segments
        if keep <= 0:
            return 0
        try:
            names = sorted(n for n in os.listdir(spool_dir)
                           if n.startswith("flight-")
                           and n.endswith(".ndjson"))
        except OSError:
            return 0
        dropped = 0
        for name in names[:-keep]:
            try:
                os.remove(os.path.join(spool_dir, name))
                dropped += 1
            except OSError:
                pass
        if dropped:
            try:
                self._registry().flight_spool_dropped.inc(
                    {"kind": "segment"}, dropped)
            except Exception:
                pass
        return dropped

    def _rotate_divergences(self, path: str) -> int:
        """Size-capped rotation for divergences.ndjson: once the live
        file exceeds ``divergence_max_bytes`` it shifts to ``.1`` (and
        ``.1``->``.2``, ...), keeping the newest ``max_spool_segments``
        rotated segments. Returns segments dropped off the end.

        Every step of the replace chain goes through the storage shim:
        each rename either fully lands or leaves the previous file
        intact (os.replace is atomic), so a mid-rotation EIO is a
        counted degrade that leaves every segment a loadable NDJSON
        prefix — never a torn or vanished file."""
        from ..resilience import storage as st

        cap = self.divergence_max_bytes
        if cap <= 0:
            return 0
        try:
            if os.path.getsize(path) < cap:
                return 0
        except OSError:
            return 0
        keep = max(1, self.max_spool_segments)
        dropped = 0
        oldest = f"{path}.{keep}"
        if os.path.exists(oldest):
            try:
                os.remove(oldest)
                dropped = 1
            except OSError:
                return 0
        for i in range(keep - 1, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                try:
                    st.atomic_replace(src, f"{path}.{i + 1}",
                                      st.SURFACE_DIVERGENCES)
                except OSError:
                    pass  # counted + degraded by the shim; chain goes on
        try:
            st.atomic_replace(path, f"{path}.1", st.SURFACE_DIVERGENCES)
        except OSError:
            return dropped
        if dropped:
            try:
                self._registry().flight_spool_dropped.inc(
                    {"kind": "divergence"}, dropped)
            except Exception:
                pass
        return dropped

    # -- auto-spool triggers

    def on_breaker_transition(self, breaker: str, frm: str, to: str) -> None:
        # forced: a breaker transition is the definitive incident
        # moment and the breaker's own reset timeout already rate-
        # limits flapping — the SLO-burn cooldown must not starve it.
        # DETACHED: the caller holds the breaker lock (every admission
        # thread contends on it via allow()/record_*), so serializing
        # the whole ring to disk inline would stall serving exactly at
        # the recovery moment; the spool snapshots the ring itself
        if not self.spool_dir:
            return
        threading.Thread(
            target=self.spool,
            kwargs={"reason": f"breaker-{breaker}-{frm}-{to}",
                    "force": True},
            daemon=True, name="flight-spool").start()

    def on_slo_burn(self, slos: Sequence[str]) -> None:
        # DETACHED like the breaker spool: the burn is observed by
        # whatever thread refreshed the SLO gauges — including the
        # /metrics scrape via the collect hook — and serializing the
        # whole ring inline would time out the scrape at incident onset
        if not self.spool_dir:
            return
        threading.Thread(
            target=self.spool,
            kwargs={"reason": "slo-" + "-".join(sorted(slos))},
            daemon=True, name="flight-spool").start()


global_flight = FlightRecorder()


def load_capture(path: str) -> List[Dict[str, Any]]:
    """Read a spooled capture (flight-*.ndjson or divergences.ndjson):
    one JSON object per line; divergence lines are unwrapped to their
    embedded record. Malformed lines are skipped, not fatal — a capture
    truncated by a dying process must still mostly load."""
    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if not isinstance(doc, dict):
                continue
            if doc.get("kind") == "divergence" and \
                    isinstance(doc.get("record"), dict):
                doc = doc["record"]
            out.append(doc)
    return out
