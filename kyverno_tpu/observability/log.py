"""Structured operational event log — JSONL with trace correlation.

The repo's observability stack answers "how fast" (/metrics), "what
happened to THIS request" (tracing), and "which rules are hot"
(analytics) — but the discrete operational events in between (breaker
transitions, quarantine enter/heal, snapshot swap/rollback, encoder
pool restarts, SLO burns, verdict divergences) were ad-hoc
``print(file=sys.stderr)`` lines or trace events nobody tails. This
module gives them ONE structured channel:

- every event is a flat dict: ``ts`` (ISO-8601 UTC), ``level``,
  ``event``, plus event-specific fields; when the emitting thread is
  inside a traced operation the event carries its ``trace_id`` so a
  log line links straight to /debug/traces;
- sinks: human-readable stderr (the ``serve`` default) and/or a
  newline-delimited JSON file (``serve --log-file PATH``) that a log
  shipper tails without parsing prose;
- emit() never raises and never blocks on anything but the file write
  lock — operational logging must not be able to take down the ladder
  it narrates.

Library default is SILENT (no sink): tests and embedding callers opt
in via configure(); the serve entrypoint configures stderr-human by
default.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, Dict, Optional

_LEVELS = ("debug", "info", "warn", "error")


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + \
        f".{int((ts % 1) * 1000):03d}Z"


class OpLog:
    """Process-wide operational event log. Thread-safe; sinks are
    reconfigurable at runtime (serve wires them from flags)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._path: Optional[str] = None   # guarded-by: _lock
        self._fh = None                    # guarded-by: _lock
        self._stderr = False               # guarded-by: _lock
        self.events_emitted = 0            # guarded-by: _lock

    # -- configuration

    def configure(self, path: Optional[str] = None,
                  stderr: Optional[bool] = None) -> None:
        """``path``: JSONL sink file (append; "" / None leaves the file
        sink untouched, "off" closes it). ``stderr``: toggle the human-
        format stderr sink. An unopenable file sink degrades the
        ``oplog`` storage surface instead of raising — stderr still
        narrates, and emit()'s re-probes retry the open."""
        from ..resilience import storage as st

        err: Optional[OSError] = None
        with self._lock:
            if path == "off":
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except Exception:
                        pass
                self._fh, self._path = None, None
            elif path:
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except Exception:
                        pass
                try:
                    self._fh = st.open_append(path, st.SURFACE_OPLOG,
                                              record=False)
                except OSError as e:
                    self._fh, err = None, e
                self._path = path  # kept: emit()'s probes retry the open
            if stderr is not None:
                self._stderr = stderr
        if err is not None:
            # recorded OUTSIDE our lock: the degrade transition's own
            # op-log event re-enters emit()
            st.storage_health(st.SURFACE_OPLOG).record_error(err, op="open")

    def reset(self) -> None:
        self.configure(path="off", stderr=False)
        with self._lock:
            self.events_emitted = 0

    @property
    def enabled(self) -> bool:
        return self._stderr or self._fh is not None \
            or self._path is not None

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {"stderr": self._stderr, "file": self._path,
                    "events_emitted": self.events_emitted}

    # -- emission

    def emit(self, event: str, level: str = "info", **fields: Any) -> None:
        if not (self._stderr or self._fh is not None
                or self._path is not None):
            with self._lock:
                self.events_emitted += 1  # counted even when unsunk (tests)
            return
        try:
            self._emit(event, level if level in _LEVELS else "info", fields)
        except Exception:
            pass  # the log must never take down what it narrates

    def _emit(self, event: str, level: str, fields: Dict[str, Any]) -> None:
        rec: Dict[str, Any] = {"ts": _iso(time.time()), "level": level,
                               "event": event}
        # trace correlation: an event emitted under a live span carries
        # that span's trace id (breaker transitions inside a dispatch
        # span link to the batch that tripped them)
        try:
            from .tracing import global_tracer

            ctx = global_tracer.current_context()
            if ctx is not None:
                rec["trace_id"] = ctx.trace_id
        except Exception:
            pass
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        # degraded-storage ladder (surface ``oplog``): the file sink is
        # drop-and-count while the disk is sick — the stderr sink keeps
        # narrating regardless. Health accounting happens AFTER our
        # (non-reentrant) lock is released, because the degrade/heal
        # transition emits an op-log event of its own.
        from ..resilience import storage as st

        health = st.storage_health(st.SURFACE_OPLOG)
        err: Optional[OSError] = None
        wrote = False
        with self._lock:
            self.events_emitted += 1
            if self._path is not None and health.allow():
                try:
                    if self._fh is None:
                        self._fh = st.open_append(self._path,
                                                  st.SURFACE_OPLOG,
                                                  record=False)
                    st.write_frame(self._fh,
                                   json.dumps(rec, default=str) + "\n",
                                   st.SURFACE_OPLOG, path=self._path,
                                   flush=True, record=False)
                    wrote = True
                except OSError as e:
                    err = e
            if self._stderr:
                extras = " ".join(
                    f"{k}={v}" for k, v in rec.items()
                    if k not in ("ts", "level", "event"))
                print(f"{rec['ts']} {level.upper():5s} {event} "
                      f"{extras}".rstrip(), file=sys.stderr)
        if err is not None:
            health.record_error(err, op="write")
        elif wrote:
            health.record_success()


global_oplog = OpLog()
