"""Metrics — counters/gauges/histograms with Prometheus exposition.

The reference instruments via OpenTelemetry with a Prometheus exporter
(pkg/metrics/metrics.go:132). This registry covers the same instrument
set (kyverno_policy_results_total, kyverno_policy_execution_duration_
seconds, kyverno_admission_requests_total, ...) plus the TPU engine's
own: batch sizes, device dispatch time, compile cache hits. Exposition
is the Prometheus text format served by the admission server or a
standalone endpoint.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


def _escape(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, and
    newline must be escaped or the line is unscrapeable."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help_: str):
        self.name, self.help = name, help_
        self._values: Dict[LabelKey, float] = {}
        # OpenMetrics counter exemplars: the LAST exemplar per series
        # (the verification layer attaches the diverging record's trace
        # id, so an alert on the counter links straight to the trace)
        self._exemplars: Dict[LabelKey, Tuple[LabelKey, float, float]] = {}
        self._lock = threading.Lock()

    def inc(self, labels: Optional[Dict[str, str]] = None, value: float = 1.0,
            exemplar: Optional[Dict[str, str]] = None) -> None:
        k = _labels_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            if exemplar:
                self._exemplars[k] = (_labels_key(exemplar), float(value),
                                      time.time())

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        """Programmatic read (tests, bench artifacts) — exposition
        parsing is for scrapers, not assertions."""
        with self._lock:
            return self._values.get(_labels_key(labels), 0.0)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        """Every labeled series as (labels dict, value) — the
        programmatic enumeration /debug/utilization renders from."""
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def remove(self, labels: Optional[Dict[str, str]] = None) -> None:
        """Drop one labeled series. Per-entity families (replica-id
        labels in the fleet layer) call this when the entity leaves so
        label cardinality stays bounded by the LIVE population, not by
        every replica that ever existed."""
        k = _labels_key(labels)
        with self._lock:
            self._values.pop(k, None)
            self._exemplars.pop(k, None)

    def _exemplar_suffix(self, k: LabelKey) -> str:
        ex = self._exemplars.get(k)
        if ex is None:
            return ""
        ex_labels, ex_value, ex_ts = ex
        body = ",".join(f'{lk}="{_escape(lv)}"' for lk, lv in ex_labels)
        return f" # {{{body}}} {ex_value} {round(ex_ts, 3)}"

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(k)} {v}"
                           + self._exemplar_suffix(k))
        return out


class Gauge(Counter):
    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = value

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


_DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Histogram with OpenMetrics exemplar support: an observation may
    carry exemplar labels (typically ``{"trace_id": ...}``) and the
    bucket it lands in remembers the LAST one — so a slow latency bucket
    links straight back to a concrete trace in ``/debug/traces``."""

    def __init__(self, name: str, help_: str, buckets: Sequence[float] = _DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = list(buckets)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._totals: Dict[LabelKey, int] = {}
        # (labelkey, bucket idx) -> (exemplar labels, value, unix ts);
        # idx == len(buckets) is the +Inf bucket
        self._exemplars: Dict[Tuple[LabelKey, int], Tuple[LabelKey, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        k = _labels_key(labels)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            if exemplar:
                self._exemplars[(k, i)] = (
                    _labels_key(exemplar), float(value), time.time())
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def _exemplar_suffix(self, k: LabelKey, i: int) -> str:
        ex = self._exemplars.get((k, i))
        if ex is None:
            return ""
        ex_labels, ex_value, ex_ts = ex
        body = ",".join(f'{lk}="{_escape(lv)}"' for lk, lv in ex_labels)
        return f" # {{{body}}} {ex_value} {round(ex_ts, 3)}"

    def collect(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for k in sorted(self._counts):
                cum = 0
                for i, (b, c) in enumerate(zip(self.buckets, self._counts[k])):
                    cum += c
                    le = f'le="{b}"'
                    out.append(f"{self.name}_bucket{_fmt_labels(k, le)} {cum}"
                               + self._exemplar_suffix(k, i))
                inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket{_fmt_labels(k, inf)} {self._totals[k]}"
                    + self._exemplar_suffix(k, len(self.buckets)))
                out.append(f"{self.name}_sum{_fmt_labels(k)} {self._sums[k]}")
                out.append(f"{self.name}_count{_fmt_labels(k)} {self._totals[k]}")
        return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()
        # the reference's instrument set (pkg/metrics)
        self.policy_results = self.counter(
            "kyverno_policy_results_total", "policy rule results by status")
        self.policy_duration = self.histogram(
            "kyverno_policy_execution_duration_seconds", "per-policy evaluation latency")
        self.admission_requests = self.counter(
            "kyverno_admission_requests_total", "admission requests handled")
        self.admission_duration = self.histogram(
            "kyverno_admission_review_duration_seconds", "admission review latency")
        self.policy_changes = self.counter(
            "kyverno_policy_changes_total", "policy create/update/delete events")
        # TPU engine instruments
        self.batch_size = self.histogram(
            "kyverno_tpu_batch_size", "resources per device dispatch",
            buckets=(1, 8, 32, 128, 512, 2048, 8192, 32768))
        self.device_dispatch = self.histogram(
            "kyverno_tpu_device_dispatch_seconds", "device program wall time")
        self.compile_cache = self.counter(
            "kyverno_tpu_compile_cache_total", "policy-set compiles by outcome")
        # content-addressed result caches (tpu/cache.py): per-resource
        # verdict-column and encode-row lookups by outcome, eviction
        # pressure, and live size — the hit RATE is the amortization
        # signal (a cold rate on a steady cluster means keys churn)
        self.verdict_cache = self.counter(
            "kyverno_tpu_verdict_cache_total",
            "verdict-column cache lookups by outcome (hit/miss/bypass)")
        self.verdict_cache_evictions = self.counter(
            "kyverno_tpu_verdict_cache_evictions_total",
            "verdict-column cache entries evicted at the LRU bound")
        self.verdict_cache_size = self.gauge(
            "kyverno_tpu_verdict_cache_size",
            "verdict-column cache entries currently held")
        self.encode_cache = self.counter(
            "kyverno_tpu_encode_cache_total",
            "encode-row cache lookups by outcome (hit/miss)")
        self.encode_cache_evictions = self.counter(
            "kyverno_tpu_encode_cache_evictions_total",
            "encode-row cache entries evicted at the LRU bound")
        # columnar resource store (cluster/columnar.py): encoded rows —
        # not JSON — are the system of record between watch event and
        # device batch. The walk counter is the feed-work gate metric:
        # an unchanged-resource rescan with the store warm must move
        # NEITHER the full-walk nor the diff-segment counter
        # (scripts_columnar_gate.sh asserts exactly that).
        self.encode_json_walks = self.counter(
            "kyverno_tpu_encode_json_walks_total",
            "full JSON flatten walks performed by the row encoders "
            "(pad resources excluded)")
        self.encode_diff_segments = self.counter(
            "kyverno_tpu_encode_diff_segments_total",
            "top-level subtree segment encodes on the incremental "
            "watch-diff path")
        self.columnar_store = self.counter(
            "kyverno_tpu_columnar_store_total",
            "columnar row-store lookups by outcome (hit/miss)")
        self.columnar_segments_reused = self.counter(
            "kyverno_tpu_columnar_segments_reused_total",
            "unchanged top-level subtrees spliced from stored segments "
            "instead of re-encoded during a watch-diff encode")
        self.columnar_gather_rows = self.counter(
            "kyverno_tpu_columnar_gather_rows_total",
            "encoded rows assembled into device batches by vectorized "
            "per-lane gather from the columnar store")
        self.columnar_store_entries = self.gauge(
            "kyverno_tpu_columnar_store_entries",
            "live encoded-resource entries across all columnar tables")
        self.columnar_store_rows = self.gauge(
            "kyverno_tpu_columnar_store_rows",
            "encoded lane rows resident in the columnar store arenas "
            "(live + not-yet-compacted dead)")
        self.columnar_store_bytes = self.gauge(
            "kyverno_tpu_columnar_store_bytes",
            "bytes held by the columnar store arenas (or mapped from "
            "disk when mmap-backed)")
        self.columnar_rebuilds = self.counter(
            "kyverno_tpu_columnar_rebuilds_total",
            "columnar mmap tables discarded at load (truncated/corrupt/"
            "mismatched) and rebuilt empty")
        self.columnar_compactions = self.counter(
            "kyverno_tpu_columnar_compactions_total",
            "columnar arena compactions reclaiming dead rows")
        # incremental report store (reports/store.py): delta folds over
        # verdict columns, journaled for crash consistency — the skip
        # counter is the zero-work proof for unchanged rescans, the
        # recovery counter labels every journal/snapshot degradation
        self.reports_resources = self.gauge(
            "kyverno_reports_resources",
            "resources with report rows held in the incremental store")
        self.reports_fold_ops = self.counter(
            "kyverno_reports_fold_ops_total",
            "report deltas folded (journal append + count update)")
        self.reports_fold_skipped = self.counter(
            "kyverno_reports_fold_skipped_total",
            "report upserts skipped as zero-work: (resource sha, "
            "policy-set key) unchanged since the last fold")
        self.reports_journal_records = self.counter(
            "kyverno_reports_journal_records_total",
            "delta records appended to the report journal")
        self.reports_journal_bytes = self.gauge(
            "kyverno_reports_journal_bytes",
            "current report journal size (resets at each compacted "
            "snapshot)")
        self.reports_snapshots = self.counter(
            "kyverno_reports_snapshots_total",
            "compacted report snapshots written (journal resets)")
        self.reports_recoveries = self.counter(
            "kyverno_reports_recoveries_total",
            "report store recovery/degradation events by reason "
            "(short_header/truncated_record/checksum/decode/duplicate/"
            "snapshot/replay/append_error)")
        self.reports_rebuilds = self.counter(
            "kyverno_reports_rebuilds_total",
            "from-scratch derived-count rebuilds (the delta-fold "
            "bit-identity oracle, also the mid-fold failure fallback)")
        # device-side string matching (tpu/dfa.py): pattern-bearing
        # cells by resolution path — device (DFA verdict stood),
        # confirm (approximate/byte-sensitive hit confirmed by the
        # scalar oracle), host (non-lowerable pattern) — plus the
        # compiled bank's size gauges (set at policy-set compile)
        self.pattern_cells = self.counter(
            "kyverno_tpu_pattern_cells_total",
            "pattern-bearing (rule, resource) cells by resolution path "
            "(device/confirm/host)")
        self.dfa_tables = self.gauge(
            "kyverno_tpu_dfa_tables",
            "compiled DFA pattern tables in the active policy set's bank")
        self.dfa_states = self.gauge(
            "kyverno_tpu_dfa_states",
            "total DFA states across the active bank's tables")
        self.dfa_bytes = self.gauge(
            "kyverno_tpu_dfa_table_bytes",
            "packed size of the active DFA bank's device arrays "
            "(stride-1 tables plus multi-stride tables)")
        # multi-stride + approximate-reduction pattern engine
        # (tpu/dfa.py): stride selection, reduction outcomes and the
        # CONFIRM traffic the approximations cost
        self.dfa_stride_tables = self.gauge(
            "kyverno_dfa_stride_tables",
            "active bank's pattern tables by chosen transition stride")
        self.dfa_stride_bytes = self.gauge(
            "kyverno_dfa_stride_table_bytes",
            "packed size of the active bank's stride>1 transition tables")
        self.dfa_approx_states_merged = self.gauge(
            "kyverno_dfa_approx_states_merged",
            "exact DFA states folded away by minimization / k-lookahead "
            "reduction across the active bank")
        self.dfa_approx_error_max = self.gauge(
            "kyverno_dfa_approx_error_max",
            "largest sampled over-approximation error among the active "
            "bank's reduced patterns (0-1)")
        self.dfa_top_collapse = self.counter(
            "kyverno_dfa_top_collapse_total",
            "patterns that fell back to accept-all TOP-collapse at "
            "compile, by reason (error_ceiling / approx_disabled / "
            "explore_overflow)")
        self.dfa_confirm_cells = self.counter(
            "kyverno_dfa_confirm_cells_total",
            "device pattern cells escalated to scalar-oracle CONFIRM "
            "(the price of over-approximated tables)")
        # pipelined scan (tpu/pipeline.py): how much host work hid
        # behind device time in the last pipelined scan (0 = strictly
        # serial, higher = more overlap), plus chunk accounting
        self.pipeline_overlap = self.gauge(
            "kyverno_tpu_pipeline_overlap_ratio",
            "(encode+device+host seconds - wall) / wall of the last "
            "pipelined scan")
        self.pipeline_chunks = self.counter(
            "kyverno_tpu_pipeline_chunks_total",
            "pipelined scan chunks by how they resolved")
        # policy observatory (observability/analytics.py): device feed
        # starvation — the fraction of device-relevant wall time the
        # accelerator sat idle waiting on host encode (rolling window;
        # the headline metric for the encode-pool roadmap item) — plus
        # continuously-incremented utilization attribution per phase
        self.feed_starvation = self.gauge(
            "kyverno_tpu_feed_starvation_ratio",
            "fraction of device-relevant wall time the device was idle "
            "waiting on host encode (rolling window, 0-1)")
        self.utilization_seconds = self.counter(
            "kyverno_tpu_utilization_seconds_total",
            "scan-ladder wall seconds by phase "
            "(encode_wait/device_busy/readback/host_assemble)")
        self.serving_flusher_seconds = self.counter(
            "kyverno_serving_flusher_seconds_total",
            "admission flusher wall seconds by state "
            "(wait_queue/evaluate/resolve/request_queue_wait)")
        # encoder pool (encode/pool.py): the supervised multiprocess
        # device feed — worker population and churn, dispatch queue
        # pressure, and per-chunk outcomes across the whole ladder
        # (ok / retried_ok / poison / encode_error / infra_fail /
        # bypass)
        self.encode_pool_workers = self.gauge(
            "kyverno_encode_pool_workers_alive",
            "encoder-pool worker processes alive and ready")
        self.encode_pool_restarts = self.counter(
            "kyverno_encode_pool_restarts_total",
            "encoder-pool workers restarted after a crash, hang, or "
            "silent heartbeat")
        self.encode_pool_queue_depth = self.gauge(
            "kyverno_encode_pool_queue_depth",
            "encode chunks queued or in flight on pool workers")
        self.encode_pool_chunks = self.counter(
            "kyverno_encode_pool_chunks_total",
            "encode chunks dispatched to the pool by outcome")
        # SLO layer (observability/analytics.py SloTracker): rolling-
        # window multi-rate burn-rate gauges; state also rides /readyz
        self.slo_admission_p99 = self.gauge(
            "kyverno_slo_admission_latency_p99_seconds",
            "admission p99 latency over the rolling window, by window")
        self.slo_admission_burn = self.gauge(
            "kyverno_slo_admission_burn_rate",
            "admission latency error-budget burn rate (1.0 = burning "
            "exactly the budget), by window")
        self.slo_scan_freshness = self.gauge(
            "kyverno_slo_scan_freshness_seconds",
            "seconds since the last completed background scan")
        self.slo_scan_freshness_burn = self.gauge(
            "kyverno_slo_scan_freshness_burn_rate",
            "scan freshness / target (>1 = scans running stale)")
        self.slo_device_coverage = self.gauge(
            "kyverno_slo_device_coverage_ratio",
            "fraction of compiled rules running on the device path")
        self.slo_breached = self.gauge(
            "kyverno_slo_breached",
            "1 when the named SLO is currently burning past budget")
        # flight recorder (observability/flightrecorder.py): the black
        # box over the admission/scan ladder — captured records by
        # outcome, head-sampling drops, ring occupancy, auto-spools
        self.flight_records = self.counter(
            "kyverno_flight_records_total",
            "flight-recorder records captured, by outcome "
            "(ok/error/fallback/shed/confirm/cached/expired)")
        self.flight_sampled_out = self.counter(
            "kyverno_flight_sampled_out_total",
            "decisions not recorded because head-based sampling "
            "dropped them (interesting outcomes are never dropped)")
        self.flight_ring_size = self.gauge(
            "kyverno_flight_ring_records",
            "flight-recorder records currently held in the ring")
        self.flight_spools = self.counter(
            "kyverno_flight_spools_total",
            "flight-recorder ring spools to --flight-dir, by reason")
        self.flight_spool_dropped = self.counter(
            "kyverno_flight_spool_dropped_total",
            "spool segments deleted by size-capped rotation, by kind "
            "(segment = oldest flight-*.ndjson beyond the keep window, "
            "divergence = rotated-out divergences.ndjson segment)")
        # continuous shadow verification (observability/verification.py):
        # sampled oracle re-evaluation of recorded decisions — check
        # outcomes, bit-exact divergences (exemplar = originating trace
        # id), and audit-queue pressure
        self.verification_checks = self.counter(
            "kyverno_verification_checks_total",
            "shadow-verification checks by result (match/diverge/error/"
            "skipped_no_engine/skipped_impure/skipped_overflow)")
        self.verification_divergence = self.counter(
            "kyverno_verification_divergence_total",
            "recorded verdicts that did NOT match the scalar oracle at "
            "the pinned revision — the bit-identity claim failing")
        self.verification_queue_depth = self.gauge(
            "kyverno_verification_queue_depth",
            "flight records queued for shadow verification")
        self.slo_verification_divergences = self.gauge(
            "kyverno_slo_verification_divergences",
            "verdict-integrity SLO: shadow-verification divergences in "
            "the rolling window, by window (target: 0)")
        # policy-set static analysis (analysis/): witness-synthesis +
        # cross-product anomaly detection — lint run outcomes, the last
        # completed report's anomaly counts by kind, corpus size, and
        # the per-phase wall split (synthesize/evaluate/classify/
        # confirm) so a slow lint is attributable at a glance
        self.analysis_runs = self.counter(
            "kyverno_analysis_runs_total",
            "static-analysis runs by outcome (ok/aborted/error)")
        self.analysis_anomalies = self.gauge(
            "kyverno_analysis_anomalies",
            "confirmed anomalies in the last completed analysis, by "
            "kind (shadow/conflict/redundant/dead)")
        self.analysis_witnesses = self.gauge(
            "kyverno_analysis_witnesses",
            "synthesized witness resources evaluated by the last "
            "completed analysis")
        self.analysis_wall_seconds = self.gauge(
            "kyverno_analysis_wall_seconds",
            "wall seconds of the last completed analysis, by phase "
            "(synthesize/evaluate/classify/confirm)")
        # serving pipeline instruments (serving/batcher.py): queue
        # depth, batch occupancy, flush reasons, shed/expiry counters,
        # and submit-to-verdict latency (p50-p99 read from buckets)
        self.serving_queue_depth = self.gauge(
            "kyverno_serving_queue_depth",
            "admission requests waiting in the batching queue")
        self.serving_batch_size = self.histogram(
            "kyverno_serving_batch_size",
            "live requests per batched device dispatch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self.serving_batch_occupancy = self.histogram(
            "kyverno_serving_batch_occupancy",
            "live requests / padded bucket capacity per flush",
            buckets=(0.125, 0.25, 0.5, 0.75, 0.9, 1.0))
        self.serving_flush_total = self.counter(
            "kyverno_serving_flush_total", "batch flushes by trigger reason")
        self.serving_shed_total = self.counter(
            "kyverno_serving_shed_total",
            "requests shed at the queue high-water mark by outcome")
        self.serving_deadline_expired_total = self.counter(
            "kyverno_serving_deadline_expired_total",
            "requests whose deadline expired while queued")
        self.serving_request_latency = self.histogram(
            "kyverno_serving_request_latency_seconds",
            "admission submit-to-verdict latency")
        # admission scheduling (serving/scheduler.py + queue.py): the
        # per-class view of the pipeline — queue pressure by priority
        # tier, request resolutions by class and path, and the hedged
        # scalar-vs-device races by winner. The class label is the
        # PRIORITY TIER (critical/default/bulk), never the tenant —
        # tenant-level fairness stays internal so label cardinality is
        # bounded at three no matter how many namespaces submit
        self.serving_class_queue_depth = self.gauge(
            "kyverno_serving_class_queue_depth",
            "admission requests waiting in the batching queue, by "
            "priority class")
        self.serving_class_requests = self.counter(
            "kyverno_serving_class_requests_total",
            "admission requests by priority class and resolution "
            "outcome (batched/cached/hedged/shed/expired)")
        self.serving_hedge = self.counter(
            "kyverno_serving_hedge_total",
            "hedged scalar dispatches racing an in-flight device batch, "
            "by winner (scalar/device/device_error/expired/error)")
        # fleet layer (fleet/): multi-replica membership, rendezvous
        # shard ownership, and cache peering. Peer labels are replica
        # ids — cardinality is bounded by the (small, operator-
        # configured) fleet size, so per-peer families are safe here
        # where per-tenant ones would not be
        self.fleet_replicas = self.gauge(
            "kyverno_fleet_replicas",
            "live replicas in this replica's membership view "
            "(self included)")
        self.fleet_is_leader = self.gauge(
            "kyverno_fleet_is_leader",
            "1 when this replica is the fleet leader (lowest live id)")
        self.fleet_epoch = self.gauge(
            "kyverno_fleet_epoch",
            "membership-change epoch the current shard map was "
            "computed at")
        self.fleet_shards_owned = self.gauge(
            "kyverno_fleet_shards_owned",
            "resource-keyspace shards this replica currently owns")
        self.fleet_shard_reassignments = self.counter(
            "kyverno_fleet_shard_reassignments_total",
            "shards that moved INTO this replica's ownership, by "
            "reason (initial/membership)")
        self.fleet_shard_staleness = self.gauge(
            "kyverno_fleet_shard_staleness_seconds",
            "seconds by which the oldest owned shard trails the last "
            "scan tick (takeover shards inherit the dead owner's last "
            "gossiped stamp until rescanned)")
        self.fleet_heartbeats = self.counter(
            "kyverno_fleet_heartbeats_total",
            "outbound membership heartbeats by peer and outcome")
        self.fleet_peer_fetch = self.counter(
            "kyverno_fleet_peer_fetch_total",
            "verdict-cache peer fetch keys by peer and outcome "
            "(hit/miss/error/rejected)")
        self.fleet_peer_rejects = self.counter(
            "kyverno_fleet_peer_rejects_total",
            "peer cache entries rejected at receive verification by "
            "reason (checksum/key_mismatch/shape/decode) — every "
            "reject is served as a miss, never a wrong verdict")
        self.fleet_gossip = self.counter(
            "kyverno_fleet_gossip_total",
            "async verdict-column gossip by outcome "
            "(sent/received/error/dropped)")
        # fleet telemetry plane (fleet/telemetry.py): the leader pulls
        # checksummed per-replica snapshots on the heartbeat cadence
        # and folds counter DELTAS into the kyverno_fleet_agg_*
        # families — a restarted replica resetting to zero can never
        # drive an aggregate backwards, and a snapshot failing the
        # trust ladder is dropped and counted, never merged wrong.
        # Replica labels are bounded by the operator-configured fleet
        # size (the PR 15 rule) and pruned when a replica leaves
        self.fleet_telemetry_pulls = self.counter(
            "kyverno_fleet_telemetry_pulls_total",
            "leader-side telemetry snapshot pulls by peer and outcome "
            "(ok/rejected/error)")
        self.fleet_telemetry_rejects = self.counter(
            "kyverno_fleet_telemetry_rejects_total",
            "telemetry snapshots dropped at the aggregation trust "
            "ladder by reason (checksum/schema_version/stale_seq/"
            "epoch/stale/decode) — a rejected snapshot is never "
            "merged wrong")
        self.fleet_agg_admissions = self.counter(
            "kyverno_fleet_agg_admission_requests_total",
            "fleet-wide admission requests folded from per-replica "
            "telemetry counter deltas (leader-maintained)")
        self.fleet_agg_admission_slow = self.counter(
            "kyverno_fleet_agg_admission_slow_total",
            "fleet-wide admissions slower than the p99 target, folded "
            "from per-replica telemetry counter deltas")
        self.fleet_agg_scan_ticks = self.counter(
            "kyverno_fleet_agg_scan_ticks_total",
            "fleet-wide background scan ticks folded from per-replica "
            "telemetry counter deltas")
        self.fleet_agg_verification_checked = self.counter(
            "kyverno_fleet_agg_verification_checked_total",
            "fleet-wide shadow-verification checks folded from "
            "per-replica telemetry counter deltas")
        self.fleet_agg_divergence = self.counter(
            "kyverno_fleet_agg_divergence_total",
            "fleet-wide shadow-verification divergences folded from "
            "per-replica telemetry counter deltas — nonzero flips the "
            "fleet-degraded advisory bit")
        self.fleet_agg_burn = self.gauge(
            "kyverno_fleet_agg_admission_burn_rate",
            "fleet-wide admission SLO burn computed over the merged "
            "per-replica window samples, by window")
        self.fleet_agg_replicas_reporting = self.gauge(
            "kyverno_fleet_agg_replicas_reporting",
            "replicas with a fresh accepted telemetry snapshot in the "
            "leader's aggregation view")
        self.fleet_agg_snapshot_age = self.gauge(
            "kyverno_fleet_agg_snapshot_age_seconds",
            "age of the last accepted telemetry snapshot by replica "
            "(series pruned when a replica leaves the live set)")
        self.fleet_agg_degraded = self.gauge(
            "kyverno_fleet_agg_degraded",
            "1 when the fleet-aggregated divergence total is nonzero "
            "(the advisory fleet-degraded bit /readyz surfaces)")
        # batched mutation (mutation/): device triage over the compiled
        # mutate bank, patch application by source, degradation-ladder
        # fallbacks, and shadow-verification divergence — the mutate
        # mirror of the validate serving instruments
        self.mutate_triage = self.counter(
            "kyverno_mutate_triage_total",
            "needs-mutation triage batches by outcome "
            "(device/fallback/cached)")
        self.mutate_triage_rows = self.counter(
            "kyverno_mutate_triage_rows_total",
            "triage (rule, resource) cells by result "
            "(positive/negative/host)")
        self.mutate_patches = self.counter(
            "kyverno_mutate_patches_total",
            "mutate patch applications by source (template/scalar)")
        self.mutate_patch_fallbacks = self.counter(
            "kyverno_mutate_patch_fallbacks_total",
            "template-stamp passes degraded to the scalar patcher")
        self.mutate_divergence = self.counter(
            "kyverno_mutate_divergence_total",
            "shadow-verified mutate records whose patched output "
            "differed from the scalar oracle's")
        self.mutate_duration = self.histogram(
            "kyverno_mutate_duration_seconds",
            "batched mutate handling latency (triage + patch)")
        # resilience layer (resilience/): breaker state machine, scalar
        # fallback routing, retry outcomes, injected faults
        self.breaker_state = self.gauge(
            "kyverno_tpu_breaker_state",
            "circuit breaker state (0 closed, 1 open, 2 half-open)")
        self.breaker_transitions = self.counter(
            "kyverno_tpu_breaker_transitions_total",
            "circuit breaker state transitions")
        self.breaker_fallback = self.counter(
            "kyverno_tpu_breaker_fallback_total",
            "batches completed by the scalar oracle instead of the device")
        self.retry_attempts = self.counter(
            "kyverno_resilience_retry_total",
            "retried call outcomes by site (recovered counts extra attempts)")
        self.faults_injected = self.counter(
            "kyverno_resilience_faults_injected_total",
            "injected faults fired by site and mode")
        # degraded-storage ladder (resilience/storage.py): OS-level I/O
        # errors per durability surface, which surfaces are currently
        # running in their memory mode, and completed heals — a full
        # disk must be an alert with a bounded blast radius, never a
        # crash or a wrong verdict
        self.storage_errors = self.counter(
            "kyverno_storage_errors_total",
            "storage I/O errors by durability surface and error kind")
        self.storage_degraded = self.gauge(
            "kyverno_storage_degraded",
            "1 while a durability surface runs degraded (memory mode)")
        self.storage_heals = self.counter(
            "kyverno_storage_heals_total",
            "degraded->ok heals per durability surface")
        # policy-set lifecycle (lifecycle/manager.py): the served
        # compiled revision, hot-swap promotions, compile-ahead
        # failures, and the quarantine population — a policy churn
        # problem must be an alert, not a latency mystery
        self.policyset_revision = self.gauge(
            "kyverno_policyset_revision",
            "policy-set revision of the active compiled version")
        self.policyset_swaps = self.counter(
            "kyverno_policyset_swaps_total",
            "compiled policy-set versions promoted (atomic hot swaps)")
        self.policyset_compile_failures = self.counter(
            "kyverno_policyset_compile_failures_total",
            "compile-ahead failures by kind (set-level rollbacks)")
        self.policyset_quarantined = self.gauge(
            "kyverno_policyset_quarantined",
            "policies currently quarantined off the device path")
        # scan_stream phase split (SURVEY §5: encode/device/host costs)
        self.scan_encode_seconds = self.histogram(
            "kyverno_tpu_scan_encode_seconds", "host encode time per scan")
        self.scan_device_seconds = self.histogram(
            "kyverno_tpu_scan_device_seconds", "device wall time per scan")
        self.scan_host_seconds = self.histogram(
            "kyverno_tpu_scan_host_seconds", "host completion time per scan")
        # event generator accounting (observability/events.py): drops
        # are an overload signal that must be scrapeable, not an
        # attribute on a Python object nobody reads
        self.events_emitted = self.counter(
            "kyverno_events_emitted_total",
            "policy events delivered to the sink")
        self.events_dropped = self.counter(
            "kyverno_events_dropped_total",
            "policy events dropped on queue overflow")
        # per-rule analytics exposition: a scrape-time pseudo-instrument
        # rendering kyverno_rule_* / kyverno_policy_device_coverage with
        # bounded label cardinality (top-K policies + one _overflow
        # series). Lazy import: analytics must stay importable first.
        from .analytics import RuleStatsCollector

        self.rule_stats = RuleStatsCollector()
        self._instruments["kyverno_rule_stats"] = self.rule_stats
        # pre-collect hooks: window-decaying gauges (SLO burn rates,
        # starvation ratio) refresh here so a scrape between records
        # still sees live values
        self._collect_hooks: List[Any] = []

    def add_collect_hook(self, fn) -> None:
        with self._lock:
            if fn not in self._collect_hooks:
                self._collect_hooks.append(fn)

    def counter(self, name: str, help_: str) -> Counter:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = Counter(name, help_)
                self._instruments[name] = inst
            return inst  # type: ignore[return-value]

    def gauge(self, name: str, help_: str) -> Gauge:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = Gauge(name, help_)
                self._instruments[name] = inst
            return inst  # type: ignore[return-value]

    def histogram(self, name: str, help_: str, buckets: Sequence[float] = _DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = Histogram(name, help_, buckets)
                self._instruments[name] = inst
            return inst  # type: ignore[return-value]

    # exemplars are an OpenMetrics construct: a scraper that negotiates
    # the plain text format would reject the mid-line '#'. The HTTP
    # surfaces serve this content type (and the terminator below) so
    # the right parser is selected; exposition() itself stays a plain
    # string for tests and programmatic readers.
    OPENMETRICS_CONTENT_TYPE = \
        "application/openmetrics-text; version=1.0.0; charset=utf-8"

    def exposition(self) -> str:
        lines: List[str] = []
        with self._lock:
            insts = list(self._instruments.values())
            hooks = list(getattr(self, "_collect_hooks", ()))
        for hook in hooks:
            try:
                hook()
            except Exception:
                pass  # a broken hook must not break the scrape
        for inst in insts:
            lines.extend(inst.collect())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"

    def http_body(self) -> "Tuple[bytes, str]":
        """(body, content-type) for a /metrics endpoint: OpenMetrics
        framing — exposition plus the mandatory '# EOF' terminator."""
        return (self.exposition() + "# EOF\n").encode(), \
            self.OPENMETRICS_CONTENT_TYPE


global_registry = MetricsRegistry()
