"""Per-phase profiling — where did the scan's wall time actually go?

SURVEY §5 splits accelerator scan cost into encode / device / host
phases; tuning any of them requires attribution first. The profiler
accumulates (seconds, calls) per named phase process-wide; the engine
hot paths mark ``encode`` / ``compile`` / ``dispatch`` / ``readback`` /
``host_complete``, and consumers (``apply --profile``, ``bench.py
--phases``, ``/debug/state``) read the breakdown without re-timing
anything.

Also here: the thread-local dispatch-path marker (device vs scalar
fallback — the serving pipeline reads it to name the per-request
dispatch span honestly) and the one-shot ``jax.profiler`` capture
latch behind ``KYVERNO_TPU_XLA_TRACE_DIR``.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

PHASE_ENCODE = "encode"
PHASE_COMPILE = "compile"
PHASE_DISPATCH = "dispatch"
PHASE_READBACK = "readback"
PHASE_HOST_COMPLETE = "host_complete"
# pipelined-scan consumer idle time: the main loop blocked on the
# encode queue with nothing in flight — the device was starving
# (observability/analytics.py StarvationTracker owns the windowed view)
PHASE_ENCODE_WAIT = "encode_wait"

# canonical print order; unknown phases sort after these
PHASE_ORDER = (PHASE_ENCODE, PHASE_ENCODE_WAIT, PHASE_COMPILE,
               PHASE_DISPATCH, PHASE_READBACK, PHASE_HOST_COMPLETE)


class PhaseProfiler:
    """Thread-safe accumulator of per-phase wall time."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._seconds[name] = self._seconds.get(name, 0.0) + seconds
            self._calls[name] = self._calls.get(name, 0) + 1

    def breakdown(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "seconds": round(self._seconds[name], 6),
                    "calls": self._calls[name],
                    "mean_ms": round(
                        self._seconds[name] / self._calls[name] * 1e3, 4),
                }
                for name in self._ordered_names()
            }

    def _ordered_names(self):
        known = [p for p in PHASE_ORDER if p in self._seconds]
        extra = sorted(n for n in self._seconds if n not in PHASE_ORDER)
        return known + extra

    def render_table(self, title: str = "per-phase latency breakdown") -> str:
        """Aligned text table (the `apply --profile` output)."""
        bd = self.breakdown()
        if not bd:
            return f"{title}: no phases recorded"
        total = sum(v["seconds"] for v in bd.values())
        rows = [("phase", "seconds", "calls", "mean_ms", "share")]
        for name, v in bd.items():
            share = (v["seconds"] / total * 100.0) if total else 0.0
            rows.append((name, f"{v['seconds']:.4f}", str(v["calls"]),
                         f"{v['mean_ms']:.3f}", f"{share:5.1f}%"))
        rows.append(("total", f"{total:.4f}", "", "", "100.0%"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        lines = [title]
        for i, r in enumerate(rows):
            lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._seconds.clear()
            self._calls.clear()


global_profiler = PhaseProfiler()


# -- dispatch-path marker ---------------------------------------------------
# guarded_dispatch records HOW the last batch on this thread resolved
# (device vs scalar fallback); the serving flusher — which runs the
# evaluator inline on its own thread — reads it to name the request's
# dispatch span. Thread-local, so concurrent scanners don't cross-talk.

_tls = threading.local()

PATH_DEVICE = "device"
PATH_SCALAR_FALLBACK = "scalar_fallback"


def set_dispatch_path(path: str) -> None:
    _tls.dispatch_path = path


def last_dispatch_path(default: str = PATH_DEVICE) -> str:
    return getattr(_tls, "dispatch_path", default)


# -- optional XLA profiler capture ------------------------------------------

XLA_TRACE_ENV = "KYVERNO_TPU_XLA_TRACE_DIR"
_xla_latch_lock = threading.Lock()
_xla_captured = False


@contextmanager
def maybe_xla_trace(out_dir: Optional[str] = None):
    """Capture ONE ``jax.profiler`` trace of the wrapped region when the
    flag is set (``KYVERNO_TPU_XLA_TRACE_DIR`` or an explicit dir); a
    one-shot latch keeps steady-state dispatches unperturbed after the
    first capture. No flag -> zero-cost passthrough."""
    global _xla_captured
    target = out_dir or os.environ.get(XLA_TRACE_ENV, "")
    if not target:
        yield False
        return
    with _xla_latch_lock:
        if _xla_captured:
            yield False
            return
        _xla_captured = True
    started = False
    try:
        import jax

        jax.profiler.start_trace(target)
        started = True
    except Exception:
        pass  # profiler unavailability must not fail the dispatch
    try:
        yield started
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass


def reset_xla_trace_latch() -> None:
    """Re-arm the one-shot capture (tests / repeated profile runs)."""
    global _xla_captured
    with _xla_latch_lock:
        _xla_captured = False
