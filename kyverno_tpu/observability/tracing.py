"""Tracing — causally-connected spans across the whole request path.

The reference wraps every rule and policy evaluation in OTel spans
(pkg/tracing, engine.go:243). This layer gives the batch engine the
same causality story with real identifiers: 128-bit trace IDs, 64-bit
span IDs, and an explicit ``SpanContext`` that crosses thread
boundaries by value — the serving queue attaches the submitting
request's context to its pending-request record so the flusher thread's
queue-wait / flush / dispatch / verdict spans land in the SAME trace,
and ``parallel/sharding.py`` propagates a scan-level context to every
tile's encode/device/host spans.

Exporters are pluggable: the tracer always keeps a bounded in-memory
ring buffer (the ``/debug/traces`` source), and callers may attach an
``OTLPJsonFileExporter`` (newline-delimited OTLP-JSON, one span per
line — ``serve --trace-export PATH``) or any ``callable(Span)``.

Clock discipline: span ``start``/``end`` are ``time.monotonic()``
(comparable with the serving queue's arrival/deadline stamps, so
retroactively recorded spans — ``record_span`` — line up with live
ones); export converts to wall-clock nanoseconds via the tracer's
monotonic->epoch anchor.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

STATUS_OK = "ok"
STATUS_ERROR = "error"


def new_trace_id() -> str:
    """128-bit trace id, lowercase hex (W3C traceparent width)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit span id, lowercase hex."""
    return os.urandom(8).hex()


@dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span: pass it by VALUE across
    threads/queues and start children with ``tracer.span(...,
    parent=ctx)`` — never rely on thread-locals across a handoff."""

    trace_id: str
    span_id: str


def context_to_wire(ctx: Optional[SpanContext]) -> Optional[Dict[str, str]]:
    """Serialize a SpanContext for a JSON RPC envelope (the fleet peer
    protocol carries the caller's context so the receiver can open a
    child span — one connected trace across replicas)."""
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


def context_from_wire(doc: Any) -> Optional[SpanContext]:
    """Parse a wire envelope back into a SpanContext; None for
    anything malformed — a corrupt envelope degrades to an unlinked
    span, never an error on the serving path."""
    if not isinstance(doc, dict):
        return None
    tid, sid = doc.get("trace_id"), doc.get("span_id")
    if not (isinstance(tid, str) and isinstance(sid, str) and tid and sid):
        return None
    return SpanContext(trace_id=tid, span_id=sid)


@dataclass
class SpanEvent:
    name: str
    timestamp: float  # monotonic, same clock as Span.start/end
    attributes: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Span:
    name: str
    context: SpanContext
    start: float
    end: float = 0.0
    parent_span_id: Optional[str] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[SpanEvent] = field(default_factory=list)
    status: str = STATUS_OK
    status_message: str = ""

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    @property
    def span_id(self) -> str:
        return self.context.span_id

    @property
    def parent(self) -> Optional[str]:
        """Parent SPAN ID (identity, not name — two nested spans with
        the same name stay distinct)."""
        return self.parent_span_id

    @property
    def duration(self) -> float:
        return (self.end or time.monotonic()) - self.start

    def add_event(self, name: str, **attributes: Any) -> None:
        self.events.append(SpanEvent(name, time.monotonic(), attributes))

    def set_status(self, status: str, message: str = "") -> None:
        self.status = status
        self.status_message = message

    def to_dict(self) -> Dict[str, Any]:
        """Plain-JSON shape for /debug/traces."""
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start": self.start,
            "duration_ms": round(self.duration * 1e3, 4),
            "status": self.status,
            **({"status_message": self.status_message}
               if self.status_message else {}),
            "attributes": dict(self.attributes),
            "events": [{"name": e.name,
                        "offset_ms": round((e.timestamp - self.start) * 1e3, 4),
                        "attributes": dict(e.attributes)} for e in self.events],
        }


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


class OTLPJsonFileExporter:
    """Newline-delimited OTLP-JSON file exporter for offline runs: one
    ExportTraceServiceRequest per line, one span per request — greppable
    and streamable, loadable by any OTLP-JSON-aware tool."""

    def __init__(self, path: str, service_name: str = "kyverno-tpu") -> None:
        from ..resilience import storage as st

        self.path = path
        self.service_name = service_name
        self._lock = threading.Lock()
        # monotonic -> wall anchor taken once, so a run's spans share a
        # consistent epoch even if the system clock steps mid-run
        self._epoch = time.time() - time.monotonic()
        try:
            self._fh = st.open_append(path, st.SURFACE_TRACE, buffering=1)
        except OSError:
            # degraded from birth (read-only/full disk at boot): spans
            # drop-and-count; __call__'s probes retry the open
            self._fh = None

    def _nanos(self, monotonic_t: float) -> str:
        return str(int((monotonic_t + self._epoch) * 1e9))

    def __call__(self, span: Span) -> None:
        otlp_span: Dict[str, Any] = {
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "name": span.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": self._nanos(span.start),
            "endTimeUnixNano": self._nanos(span.end or time.monotonic()),
            "attributes": _otlp_attrs(span.attributes),
            "events": [{
                "timeUnixNano": self._nanos(e.timestamp),
                "name": e.name,
                "attributes": _otlp_attrs(e.attributes),
            } for e in span.events],
            "status": {"code": 2 if span.status == STATUS_ERROR else 1,
                       **({"message": span.status_message}
                          if span.status_message else {})},
        }
        if span.parent_span_id:
            otlp_span["parentSpanId"] = span.parent_span_id
        line = json.dumps({"resourceSpans": [{
            "resource": {"attributes": _otlp_attrs(
                {"service.name": self.service_name})},
            "scopeSpans": [{"scope": {"name": "kyverno_tpu"},
                            "spans": [otlp_span]}],
        }]})
        # degraded-storage ladder (surface trace_export): a span is
        # never worth blocking or crashing the span-finishing thread
        # for — while the disk is sick, export is a counted drop, and
        # a due re-probe retries the open/write until it heals
        from ..resilience import storage as st

        if not st.storage_health(st.SURFACE_TRACE).allow():
            return
        with self._lock:
            try:
                if self._fh is None:
                    self._fh = st.open_append(self.path, st.SURFACE_TRACE,
                                              buffering=1)
                st.write_frame(self._fh, line + "\n", st.SURFACE_TRACE,
                               path=self.path)
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            try:
                if self._fh is not None:
                    self._fh.close()
            except Exception:
                pass


class Tracer:
    """Span factory + bounded in-memory store.

    Context propagation is a per-thread stack of live spans; an explicit
    ``parent=SpanContext`` overrides it (the cross-thread path). The
    stack is keyed by span ID, so nested spans sharing a name — or
    sibling spans on other threads — can never corrupt each other's
    parentage (the former name-keyed restore bug)."""

    def __init__(self, exporter: Optional[Callable[[Span], None]] = None,
                 max_spans: int = 4096) -> None:
        self._exporters: List[Callable[[Span], None]] = []
        if exporter is not None:
            self._exporters.append(exporter)
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._max = max_spans
        self._local = threading.local()

    # -- exporter plumbing

    def add_exporter(self, exporter: Callable[[Span], None]) -> None:
        with self._lock:
            self._exporters.append(exporter)

    def remove_exporter(self, exporter: Callable[[Span], None]) -> None:
        with self._lock:
            try:
                self._exporters.remove(exporter)
            except ValueError:
                pass

    def _export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self._max:
                self._spans = self._spans[-self._max:]
            exporters = list(self._exporters)
        for exp in exporters:
            try:
                exp(span)
            except Exception:
                pass  # a broken exporter must not fail the traced path

    # -- context propagation

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_context(self) -> Optional[SpanContext]:
        """The active span's context on THIS thread — capture it before
        a queue/thread handoff and pass it as ``parent=`` on the far
        side."""
        stack = self._stack()
        return stack[-1].context if stack else None

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def add_event(self, name: str, **attributes: Any) -> None:
        """Attach an event to this thread's active span, if any — the
        hook resilience sites (breaker transitions, fault injections,
        retry attempts) use without needing a span handle."""
        span = self.current_span()
        if span is not None:
            span.add_event(name, **attributes)

    # -- span lifecycle

    def _make_span(self, name: str, parent: Optional[SpanContext],
                   attributes: Dict[str, Any]) -> Span:
        if parent is None:
            parent = self.current_context()
        ctx = SpanContext(
            trace_id=parent.trace_id if parent else new_trace_id(),
            span_id=new_span_id())
        return Span(name=name, context=ctx, start=time.monotonic(),
                    parent_span_id=parent.span_id if parent else None,
                    attributes=dict(attributes))

    @contextmanager
    def span(self, name: str, parent: Optional[SpanContext] = None,
             **attributes: Any):
        """Start a span as a child of ``parent`` (explicit cross-thread
        context) or of this thread's current span."""
        s = self._make_span(name, parent, attributes)
        stack = self._stack()
        stack.append(s)
        try:
            yield s
        except Exception as e:
            s.set_status(STATUS_ERROR, f"{type(e).__name__}: {e}")
            raise
        finally:
            s.end = time.monotonic()
            # pop by IDENTITY: a mis-nested exit removes this span only,
            # never a same-named ancestor
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is s:
                    del stack[i]
                    break
            self._export(s)

    def start_span(self, name: str, parent: Optional[SpanContext] = None,
                   **attributes: Any) -> Span:
        """Manual lifecycle for spans that outlive a lexical scope (a
        request parked in a queue). Does NOT touch the thread-local
        stack; finish with ``end_span``."""
        return self._make_span(name, parent, attributes)

    def end_span(self, span: Span) -> None:
        if not span.end:
            span.end = time.monotonic()
        self._export(span)

    def record_span(self, name: str, start: float, end: float,
                    parent: Optional[SpanContext] = None,
                    status: str = STATUS_OK, **attributes: Any) -> Span:
        """Retroactively record a span from explicit monotonic
        timestamps — how the flusher thread materializes a request's
        queue-wait span after the fact, parented into the request's
        trace via the context the queue carried across the handoff."""
        ctx = SpanContext(
            trace_id=parent.trace_id if parent else new_trace_id(),
            span_id=new_span_id())
        s = Span(name=name, context=ctx, start=start, end=end,
                 parent_span_id=parent.span_id if parent else None,
                 attributes=dict(attributes), status=status)
        self._export(s)
        return s

    # -- introspection

    def finished(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if name is None or s.name == name]

    def traces(self) -> Dict[str, List[Span]]:
        """Finished spans grouped by trace id (insertion-ordered)."""
        out: Dict[str, List[Span]] = {}
        for s in self.finished():
            out.setdefault(s.trace_id, []).append(s)
        return out

    def trace(self, trace_id: str) -> List[Span]:
        return [s for s in self.finished() if s.trace_id == trace_id]

    def recent_traces(self, min_duration_s: float = 0.0,
                      limit: int = 50) -> List[Dict[str, Any]]:
        """JSON-ready recent traces, newest last, filterable by total
        trace duration (max span end - min span start) — the
        /debug/traces payload."""
        out = []
        for tid, spans in self.traces().items():
            t0 = min(s.start for s in spans)
            t1 = max(s.end or s.start for s in spans)
            if (t1 - t0) < min_duration_s:
                continue
            out.append({
                "trace_id": tid,
                "duration_ms": round((t1 - t0) * 1e3, 4),
                "spans": [s.to_dict() for s in spans],
            })
        return out[-limit:]

    def reset(self) -> None:
        """Drop stored spans (tests); exporters stay attached."""
        with self._lock:
            self._spans = []


global_tracer = Tracer()
