"""Tracing — span instrumentation around encode/compile/dispatch.

The reference wraps every rule and policy evaluation in OTel spans
(pkg/tracing, engine.go:243). The batch engine's natural span points
are coarser: snapshot encode, policy-set compile, device dispatch,
host completion. Spans collect into an in-memory exporter by default;
an OTLP exporter can be plugged when the collector dependency exists.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attributes: Dict[str, Any] = field(default_factory=dict)
    parent: Optional[str] = None
    status: str = "ok"

    @property
    def duration(self) -> float:
        return (self.end or time.perf_counter()) - self.start


class Tracer:
    def __init__(self, exporter=None, max_spans: int = 4096) -> None:
        self._exporter = exporter
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._max = max_spans
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **attributes):
        parent = getattr(self._local, "current", None)
        s = Span(name=name, start=time.perf_counter(),
                 attributes=dict(attributes), parent=parent)
        self._local.current = name
        try:
            yield s
        except Exception:
            s.status = "error"
            raise
        finally:
            s.end = time.perf_counter()
            self._local.current = parent
            with self._lock:
                self._spans.append(s)
                if len(self._spans) > self._max:
                    self._spans = self._spans[-self._max:]
            if self._exporter is not None:
                try:
                    self._exporter(s)
                except Exception:
                    pass

    def finished(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if name is None or s.name == name]


global_tracer = Tracer()
