"""Continuous shadow verification — the bit-identity claim, audited.

The dispatch ladder's production claim is that every rung serves
verdicts bit-identical to the scalar oracle. Tests assert it; this
module AUDITS it continuously: a low-priority background thread
re-evaluates a sampled fraction of flight-recorded decisions through
the scalar oracle at the PINNED policy-set revision (the engine
reference each record carries — the same quarantine/host-cell oracle
machinery assemble() uses) and compares verdict columns bit-exactly.

Any divergence:

- increments ``kyverno_verification_divergence_total`` with the
  originating trace id attached as an OpenMetrics exemplar;
- persists the full record + both verdict tables to the flight spool
  (``divergences.ndjson``) for ``kyverno-tpu replay`` forensics;
- feeds the verdict-integrity SLO in SloTracker (advisory on
  ``/readyz``, like the other SLOs);
- emits a structured ``verdict_divergence`` operational log event.

Only records whose evaluation is a pure function of the record are
verified — the same eligibility predicate the verdict cache uses
(``engine.cache_eligible``): a policy doing live apiCall I/O can
legitimately answer differently five seconds later, and a false
divergence alarm is worse than no audit. Impure records count as
``skipped_impure`` so the blind spot is visible, not silent.

This is the approximate-automata architecture (PAPERS.md, arxiv
1710.08647) generalized to the whole engine: a fast evaluator backed
by an exact confirmer — PR 8 applied it per pattern cell; here the
"confirmer" runs as a sampled, continuous, production-wide audit.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .flightrecorder import FlightRecord, global_flight

Rows = List[Tuple[Tuple[str, str], int]]

_QUEUE_CAP = 512


def info_from_dict(userinfo: Optional[Dict[str, Any]]):
    """RequestInfo from a recorded (or replayed) userinfo dict."""
    from ..engine.match import RequestInfo

    u = userinfo or {}
    return RequestInfo(
        username=u.get("username", ""), uid=u.get("uid", ""),
        groups=list(u.get("groups") or []),
        roles=list(u.get("roles") or []),
        cluster_roles=list(u.get("cluster_roles") or []))


def scalar_rows(engine: Any, resource: Dict[str, Any],
                ns_labels: Optional[Dict[str, str]], operation: str,
                info: Any = None) -> Rows:
    """One (resource, request) through the scalar oracle, in the
    engine's compiled-rule row order — the exact machinery assemble()
    uses for quarantine/host cells, so the shadow comparison is against
    the same oracle the ladder itself degrades to. A policy the oracle
    cannot evaluate yields per-rule ERROR, never a crash."""
    from ..tpu.engine import _scalar_rule_verdicts, build_scan_context
    from ..tpu.evaluator import ERROR, NOT_MATCHED

    per_policy: Dict[int, Optional[Dict[str, int]]] = {}
    rows: Rows = []
    for entry in engine.cps.rules:
        if entry.policy_idx not in per_policy:
            policy = engine.cps.policies[entry.policy_idx]
            try:
                pctx = build_scan_context(policy, resource, ns_labels or {},
                                          operation, info)
                per_policy[entry.policy_idx] = _scalar_rule_verdicts(
                    engine.scalar, policy, pctx)
            except Exception:
                per_policy[entry.policy_idx] = None
        verdicts = per_policy[entry.policy_idx]
        rows.append(((entry.policy_name, entry.rule_name),
                     ERROR if verdicts is None
                     else verdicts.get(entry.rule_name, NOT_MATCHED)))
    return rows


def scalar_patched(engine: Any, resource: Dict[str, Any],
                   ns_labels: Optional[Dict[str, str]], operation: str,
                   info: Any = None) -> Dict[str, Any]:
    """The full scalar mutate chain — every policy in compiled-bank
    order through ``Engine.mutate``, patched output feeding the next
    policy — the patched-output oracle for mutate records."""
    import copy

    from ..tpu.engine import build_scan_context

    patched = copy.deepcopy(resource)
    for policy in engine.cps.policies:
        if not any(r.has_mutate() for r in policy.get_rules()):
            continue
        pctx = build_scan_context(policy, patched, ns_labels or {},
                                  operation, info)
        resp = engine.scalar.mutate(pctx)
        if resp.patched_resource is not None:
            patched = resp.patched_resource
    return patched


class ShadowVerifier:
    """Sampled oracle re-evaluation of flight records.

    ``rate`` is the fraction of captured records verified (0 = off,
    the default; ``serve --shadow-verify-rate``). Async mode runs a
    bounded-queue daemon thread that yields between records (low
    priority: a full admission queue always wins the GIL race);
    ``synchronous=True`` verifies inline at offer time (tests,
    bench)."""

    def __init__(self, metrics=None, clock=time.monotonic):
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()  # guarded-by: _lock
        # popped but not yet verified (drain waits)
        self._inflight = 0  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._stopping = False  # guarded-by: _lock
        self._rng = random.Random()
        self._registered = False
        with self._lock:
            self._reset_state_locked()

    def _reset_state_locked(self) -> None:
        self.rate = 0.0
        self.synchronous = False
        # guarded-by: _lock
        self.stats: Dict[str, int] = {
            "offered": 0, "sampled_out": 0, "checked": 0, "matched": 0,
            "divergences": 0, "skipped_no_engine": 0,
            "skipped_impure": 0, "skipped_overflow": 0, "errors": 0}

    def _registry(self):
        if self._metrics is None:
            from .metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    # -- configuration / lifecycle

    def configure(self, rate: Optional[float] = None,
                  synchronous: Optional[bool] = None) -> None:
        if rate is not None:
            self.rate = min(1.0, max(0.0, rate))
        if synchronous is not None:
            self.synchronous = synchronous
        if not self._registered:
            self._registered = True
            global_flight.add_sink(self.offer)
        if self.rate > 0 and not self.synchronous:
            self._ensure_thread()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stopping = False  # guarded-by: _lock
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="shadow-verifier")
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def reset(self) -> None:
        """Per-test isolation: stop the thread, drop the queue, zero
        the stats, disable. The sink registration is forgotten too —
        the recorder's own reset() clears its sink list, so the next
        configure() must re-register."""
        self.stop(timeout=2.0)
        with self._lock:
            self._queue.clear()
            self._inflight = 0
            self._reset_state_locked()
        self._registered = False

    # -- write side (flight recorder sink)

    def offer(self, rec: FlightRecord) -> None:
        if self.rate <= 0.0 or rec.verdicts is None:
            return
        with self._lock:
            self.stats["offered"] += 1
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            with self._lock:
                self.stats["sampled_out"] += 1
            return
        if self.synchronous:
            self._verify(rec, rec.engine)
            return
        with self._cv:
            if len(self._queue) >= _QUEUE_CAP:
                # low priority means the audit drops work, never the
                # serving path — the counter keeps the drop honest
                self.stats["skipped_overflow"] += 1
                self._count_check("skipped_overflow")
                return
            # the queue holds ITS OWN strong engine reference: the
            # recorder drops rec.engine right after the sinks run so
            # the ring cannot pin superseded compiled versions
            self._queue.append((rec, rec.engine))
            depth = len(self._queue)
            self._cv.notify()
        self._ensure_thread()
        try:
            self._registry().verification_queue_depth.set(depth)
        except Exception:
            pass

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the queue AND any in-flight check finish (tests,
        bench rollups)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._inflight:
                    return True
                pending = bool(self._queue)
            if self._thread is None or not self._thread.is_alive():
                if pending and self.rate > 0 and not self.synchronous:
                    self._ensure_thread()
                elif not pending:
                    return True
            time.sleep(0.01)
        return False

    # -- the verification loop

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait(timeout=1.0)
                if self._stopping:
                    return
                rec, engine = self._queue.popleft()
                self._inflight += 1
                depth = len(self._queue)
            try:
                self._registry().verification_queue_depth.set(depth)
            except Exception:
                pass
            try:
                self._verify(rec, engine)
            finally:
                with self._lock:
                    self._inflight -= 1
            # low priority: hand the GIL back between records so the
            # serving threads always win contention
            time.sleep(0)

    def _count_check(self, result: str) -> None:
        try:
            self._registry().verification_checks.inc({"result": result})
        except Exception:
            pass

    def _bump(self, key: str) -> None:
        with self._lock:
            self.stats[key] += 1

    def _verify(self, rec: FlightRecord, engine: Any = None) -> None:
        if engine is None:
            engine = rec.engine
        if engine is None or not isinstance(rec.resource, dict) \
                or rec.verdicts is None:
            self._bump("skipped_no_engine")
            self._count_check("skipped_no_engine")
            return
        if rec.kind == "mutate":
            self._verify_mutate(rec, engine)
            return
        try:
            eligible = bool(engine.cache_eligible)
        except Exception:
            eligible = False
        if not eligible:
            self._bump("skipped_impure")
            self._count_check("skipped_impure")
            return
        try:
            expected = scalar_rows(engine, rec.resource, rec.ns_labels,
                                   rec.operation,
                                   info_from_dict(rec.userinfo))
        except Exception:
            self._bump("errors")
            self._count_check("error")
            return
        got = list(rec.verdicts)
        diverged = {k: int(v) for k, v in got} != \
            {k: int(v) for k, v in expected}
        self._bump("checked")
        try:
            from .analytics import global_slo

            global_slo.record_verification(diverged)
        except Exception:
            pass
        if not diverged:
            self._bump("matched")
            self._count_check("match")
            return
        self._bump("divergences")
        self._count_check("diverge")
        try:
            reg = self._registry()
            reg.verification_divergence.inc(
                exemplar=({"trace_id": rec.trace_id}
                          if rec.trace_id else None))
        except Exception:
            pass
        try:
            global_flight.spool_divergence(
                rec.to_dict(), expected, got)
        except Exception:
            pass
        try:
            from .log import global_oplog

            diff_cells = [
                f"{p}/{r}:{dict(expected).get((p, r))}!={c}"
                for (p, r), c in got
                if dict(expected).get((p, r)) != int(c)][:5]
            global_oplog.emit(
                "verdict_divergence", level="error",
                record_trace_id=rec.trace_id or None,
                resource_sha=rec.resource_sha, path=rec.path,
                policyset_revision=rec.revision, cells=diff_cells)
        except Exception:
            pass

    def _verify_mutate(self, rec: FlightRecord, engine: Any) -> None:
        """Mutate records diff the PATCHED OUTPUT, not the triage rows:
        HOST rows are routing, and the all-HOST fallback column is
        correct by construction (everything scalar-patches) — a row
        diff would false-alarm on every degraded batch. The claim under
        audit is bit-identity of the served patched body against a full
        scalar re-patch at the pinned revision."""
        try:
            eligible = bool(engine.mutate_cache_eligible)
        except Exception:
            eligible = False
        if not eligible:
            # a mutate rule with live context can legitimately patch
            # differently on replay — visible blind spot, not an alarm
            self._bump("skipped_impure")
            self._count_check("skipped_impure")
            return
        try:
            expected = scalar_patched(engine, rec.resource,
                                      rec.ns_labels, rec.operation,
                                      info_from_dict(rec.userinfo))
        except Exception:
            self._bump("errors")
            self._count_check("error")
            return
        from .flightrecorder import patched_digest

        got = rec.patched if rec.patched is not None else rec.resource
        got_sha = rec.patched_sha or patched_digest(got)
        diverged = got != expected \
            or got_sha != patched_digest(expected)
        self._bump("checked")
        try:
            from .analytics import global_slo

            global_slo.record_verification(diverged)
        except Exception:
            pass
        if not diverged:
            self._bump("matched")
            self._count_check("match")
            return
        self._bump("divergences")
        self._count_check("diverge")
        try:
            reg = self._registry()
            reg.mutate_divergence.inc(
                exemplar=({"trace_id": rec.trace_id}
                          if rec.trace_id else None))
            reg.verification_divergence.inc(
                exemplar=({"trace_id": rec.trace_id}
                          if rec.trace_id else None))
        except Exception:
            pass
        try:
            doc = rec.to_dict()
            doc["expected_patched"] = expected
            global_flight.spool_divergence(doc, [], list(rec.verdicts))
        except Exception:
            pass
        try:
            from .log import global_oplog

            global_oplog.emit(
                "mutate_divergence", level="error",
                record_trace_id=rec.trace_id or None,
                resource_sha=rec.resource_sha, path=rec.path,
                policyset_revision=rec.revision,
                patched_sha=got_sha,
                expected_sha=patched_digest(expected))
        except Exception:
            pass

    # -- read side

    def state(self) -> Dict[str, Any]:
        with self._lock:
            queued = len(self._queue)
            stats = dict(self.stats)
        return {"rate": self.rate, "synchronous": self.synchronous,
                "queued": queued,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "stats": stats}

    def totals(self) -> Dict[str, int]:
        """Lifetime checked/divergence counts — the monotonic half the
        fleet telemetry snapshot ships so the leader can delta-merge
        divergence across replicas (fleet/telemetry.py)."""
        with self._lock:
            return {"checked": self.stats["checked"],
                    "divergences": self.stats["divergences"]}


global_verifier = ShadowVerifier()
