"""Device-mesh parallelism for the scan engine."""

from .sharding import ShardedScanner, make_mesh, make_mesh_2d
