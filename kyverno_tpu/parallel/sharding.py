"""Sharded batch evaluation over a jax.sharding.Mesh.

The scan workload is data-parallel over resources: every batch lane has
a leading N axis, the compiled program is elementwise across it, and
per-rule verdict counts are the only cross-device reduction (XLA lowers
the sum over the sharded axis to an ICI all-reduce / reduce-scatter).
This mirrors how the reference scales scans — sharding the resource
keyspace across workers and replicas (SURVEY §2.7) — except the shards
are TPU cores on one mesh instead of goroutine pools.

Policies are replicated (they are compile-time constants baked into the
program); resources shard. For multi-host, the same program runs under
jax.distributed with the mesh spanning hosts — DCN carries only the
final counts, ICI the within-slice reductions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api.policy import ClusterPolicy
from ..tpu.compiler import CompiledPolicySet, compile_policy_set
from ..tpu.evaluator import build_program
from ..tpu.flatten import EncodeConfig, encode_resources_vocab
from ..tpu.metadata import encode_metadata


def make_mesh(devices: Optional[Sequence] = None, axis: str = "data") -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def make_mesh_2d(
    hosts: int,
    per_host: int,
    devices: Optional[Sequence] = None,
    axes: Tuple[str, str] = ("hosts", "data"),
) -> Mesh:
    """Two-axis (hosts, devices-per-host) mesh — the multi-host shape
    (SURVEY §2.7): the tile stream shards over the host axis (DCN
    boundary), per-tile resources over the intra-host axis (ICI), and
    verdict-count reductions cross both. On real multi-host topology
    the same axes map onto jax.distributed process boundaries."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < hosts * per_host:
        raise ValueError(
            f"need {hosts * per_host} devices for a {hosts}x{per_host} mesh, "
            f"have {len(devices)}")
    arr = np.array(devices[: hosts * per_host]).reshape(hosts, per_host)
    return Mesh(arr, axes)


class ShardedScanner:
    """Compile once, evaluate resource batches sharded across a mesh.

    The jitted step returns (verdicts, counts): the (rules, N) verdict
    table sharded over N, plus per-(rule, verdict-class) totals reduced
    across devices — the scan-service summary used for report rollups.
    """

    NUM_CLASSES = 7  # evaluator.NUM_VERDICT_CLASSES (incl. HOST/CONFIRM)

    def __init__(
        self,
        policies: Sequence[ClusterPolicy],
        mesh: Optional[Mesh] = None,
        encode_cfg: Optional[EncodeConfig] = None,
        meta_cfg=None,
        exceptions: Sequence = (),
        data_sources=None,
    ):
        self.cps: CompiledPolicySet = compile_policy_set(
            policies, encode_cfg, meta_cfg, data_sources)
        self.exceptions = list(exceptions)
        self.mesh = mesh if mesh is not None else make_mesh()
        # resources shard over ALL mesh axes jointly: on a 1-D mesh
        # that is plain data parallelism; on a (hosts, data) mesh the
        # N axis splits host-major, so each host owns a contiguous
        # tile range and ICI carries the within-host shards
        self.axes: Tuple[str, ...] = tuple(self.mesh.axis_names)
        self.axis = self.axes[0]
        self._raw_fn = build_program(
            self.cps.device_programs, self.cps.encode_cfg.max_instances,
            dfa=self.cps.dfa,
        )
        repl = NamedSharding(self.mesh, P())
        # vocabulary-axis buckets grow monotonically so tile-to-tile
        # vocabulary size changes never change the jitted shapes; the
        # rows axis starts small (typical resources use a fraction of
        # max_rows) and grows the same way
        self._vbucket = 1024
        self._sbucket = 256
        self._rbucket = min(64, self.cps.encode_cfg.max_rows)

        def step(batch: Dict[str, jnp.ndarray]):
            verdicts = self._raw_fn(batch)  # (rules, N)
            counts = jnp.stack(
                [(verdicts == c).sum(axis=1) for c in range(self.NUM_CLASSES)],
                axis=-1,
            )  # (rules, classes) — cross-device reduction over the N shard
            # verdicts ride D2H every tile: 6 classes fit in uint8, a
            # 4x smaller readback on bandwidth-constrained links
            return verdicts.astype(jnp.uint8), counts

        # input shardings come from the committed arrays put() produces:
        # per-resource lanes shard over the mesh, vocabulary lanes
        # replicate (they are the per-tile "embedding tables")
        self._step = jax.jit(
            step,
            out_shardings=(NamedSharding(self.mesh, P(None, self.axes)), repl),
        )
        # recording trace: which compact lanes does THIS program read?
        # encode() drops everything else before transfer (meta lanes a
        # policy set never touches are most of the per-resource bytes)
        self._used_keys = self._record_used_keys()

    def _record_used_keys(self) -> set:
        from ..tpu.evaluator import Ctx, densify, eval_rule

        vb = encode_resources_vocab([{}, {}], self.cps.encode_cfg,
                                    self.cps.byte_paths, self.cps.key_byte_paths)
        meta = encode_metadata([{}, {}], cfg=self.cps.meta_cfg)
        probe = vb.to_host(meta, self._vbucket, self._sbucket)
        used: set = set()

        def run(batch):
            view = densify(batch, record=True)
            ctx = Ctx(view, self.cps.encode_cfg.max_instances)
            outs = [eval_rule(ctx, p) for p in self.cps.device_programs]
            used.update(view.used_keys)
            return outs

        jax.eval_shape(run, probe)
        # structural keys the step itself needs even if no rule reads them
        used.update({"row_idx", "vocab_valid", "fallback", "meta_fallback"})
        self._meta_need = {k[len("meta_"):] for k in used if k.startswith("meta_")}
        return used

    # vocabulary lanes are replicated; everything else leads with N and
    # shards across the mesh axes
    @staticmethod
    def _replicated_key(k: str) -> bool:
        return k.startswith("vocab_") or k in ("pool_svocab", "pool_slen")

    @property
    def n_devices(self) -> int:
        return self.mesh.size

    def pad(self, n: int) -> int:
        """The batch size ``n`` resources actually evaluate at: the
        power-of-two batch bucket (encode/tasks.py encode_vocab_host —
        bounded jit-shape churn), rounded to the mesh multiple."""
        b = 16
        while b < n:
            b *= 2
        d = self.n_devices
        return ((b + d - 1) // d) * d

    def encode(self, resources, namespace_labels=None, operations=None,
               content_hashes=None):
        # the ONE vocab-encode body, shared with the encoder-pool
        # workers (encode/tasks.py run_vocab drives the same function
        # against the shipped profile) so pooled and in-process encodes
        # cannot drift
        from ..cluster.columnar import get_store
        from ..encode.tasks import encode_vocab_host

        store = get_store()
        if store is not None and store.enabled:
            # columnar feed: rows gather from the store (misses
            # segment-encode into it) instead of re-walking JSON. The
            # caller-provided content hashes skip re-serializing
            # unchanged bodies; pad resources hash on the fly.
            hashes = list(content_hashes or [])

            def encoder(res, cfg, bp, kbp):
                return store.encode_vocab(res, cfg, bp, kbp,
                                          hashes=hashes[: len(res)])
        else:
            # late-bound through THIS module so a patched
            # sharding.encode_resources_vocab still intercepts
            def encoder(*a, **kw):
                return encode_resources_vocab(*a, **kw)

        host, n, buckets = encode_vocab_host(
            resources, namespace_labels, operations,
            self.cps.encode_cfg, self.cps.byte_paths,
            self.cps.key_byte_paths, self.cps.meta_cfg,
            getattr(self, "_meta_need", None),
            getattr(self, "_used_keys", None),
            self.n_devices,
            (self._vbucket, self._sbucket, self._rbucket),
            encoder=encoder)
        self._vbucket, self._sbucket, self._rbucket = buckets
        return host, n

    def scan_device(self, resources, namespace_labels=None, operations=None) -> Tuple[np.ndarray, np.ndarray]:
        """Device layer only: (verdicts (device_rules, n), counts).
        Verdicts may contain HOST(5) for resources exceeding encode
        caps, and host-fallback rules are absent — use scan() for the
        complete, resolved result."""
        batch, n = self.encode(resources, namespace_labels, operations)
        verdicts, counts = self._step(self.put(batch))
        return np.asarray(verdicts)[:, :n], np.asarray(counts)

    def scan(self, resources, namespace_labels=None, operations=None):
        """Complete ScanResult over ALL rules: device verdicts merged
        with scalar-engine completions (host rules + capped resources) —
        HOST never escapes.

        Resilience ladder (resilience/): an encode failure quarantines
        hostile resources via TpuEngine.scan; a device failure (raised,
        injected, or wrong-shaped) trips the shared TPU breaker and the
        whole batch completes on the scalar oracle — bit-identical
        verdicts, the scan never aborts."""
        from ..tpu.engine import TpuEngine
        from ..tpu.evaluator import HOST

        eng = TpuEngine(cps=self.cps, exceptions=self.exceptions)
        try:
            batch, n = self.encode(resources, namespace_labels, operations)
        except Exception:
            return eng.scan(resources, namespace_labels, operations)
        D = len(self.cps.device_programs)

        def run():
            from ..observability.analytics import class_counts

            v, c = self._step(self.put(batch))
            v = np.asarray(v)
            # the step's cross-device reduction doubles as the rule-
            # analytics source: drop the mesh-pad columns and stash for
            # the assemble() below
            eng.set_pending_counts(
                np.asarray(c).astype(np.int64) - class_counts(v[:, n:]))
            return v[:, :n].astype(np.int32)

        table = eng.guarded_dispatch(run, (D, n))
        if table is None:
            table = np.full((D, len(resources)), HOST, dtype=np.int32)
        return eng.assemble(table, resources, namespace_labels, operations)

    def put(self, batch: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
        """Place a host batch on the mesh — per-resource lanes sharded
        over the mesh axes, vocabulary lanes replicated — in ONE async
        device_put over the whole lane dict (per-lane puts pay a link
        round-trip each; the batched put streams at full H2D bandwidth
        and overlaps with in-flight compute)."""
        data = NamedSharding(self.mesh, P(self.axes))
        repl = NamedSharding(self.mesh, P())
        return jax.device_put(
            batch,
            {k: (repl if self._replicated_key(k) else data) for k in batch})

    def scan_stream(
        self,
        resources,
        tile: int = 8192,
        namespace_labels=None,
        operations=None,
        complete_host: bool = True,
        in_flight: int = 3,
    ):
        """Tiled streaming scan for snapshots larger than one device
        batch (BASELINE config #2 at 100k resources). Every tile is
        padded to the same shape so the jitted step compiles once; JAX
        async dispatch overlaps device work on up to ``in_flight`` tiles
        with the host's encode of the next tiles. Returns (ScanResult,
        stats) where stats carries the honest cost split: encode
        seconds, device wall seconds, host completion seconds, and
        host-resolved cell count.
        """
        import time

        from ..observability.metrics import global_registry
        from ..observability.profiling import (PHASE_DISPATCH, PHASE_ENCODE,
                                               PHASE_HOST_COMPLETE,
                                               PHASE_READBACK, global_profiler)
        from ..observability.tracing import global_tracer
        from ..tpu.engine import TpuEngine
        from ..tpu.evaluator import HOST

        tile = self.pad(tile)
        n = len(resources)
        stats = {"encode_s": 0.0, "device_s": 0.0, "host_s": 0.0,
                 "host_cells": 0, "tiles": 0, "tile": tile}
        eng = (TpuEngine(cps=self.cps, exceptions=self.exceptions)
               if complete_host else None)
        tables = []
        pending = []  # (device verdicts future, tile slice, n_valid)
        # every chunk span is an EXPLICIT child of one scan-level
        # context: tile spans stay causally connected to this scan no
        # matter which thread (or async drain order) touches them
        scan_span = global_tracer.start_span(
            "scan_stream", resources=n, tile=tile)
        scan_ctx = scan_span.context

        from ..observability.analytics import global_starvation

        def drain():
            dv, sl, nv = pending.pop(0)
            t0 = time.perf_counter()
            with global_profiler.phase(PHASE_READBACK), \
                    global_tracer.span("scan_device_wait", parent=scan_ctx,
                                       tile=nv):
                table = np.asarray(dv)[:, :nv]  # blocks on the device
            dt = time.perf_counter() - t0
            stats["device_s"] += dt
            global_starvation.record(busy_s=dt)
            if eng is not None:
                t0 = time.perf_counter()
                with global_profiler.phase(PHASE_HOST_COMPLETE), \
                        global_tracer.span("scan_host_complete",
                                           parent=scan_ctx, tile=nv):
                    res = eng.assemble(
                        table, resources[sl],
                        namespace_labels,
                        operations[sl] if operations else None,
                    )
                # HOST and CONFIRM cells both resolved on the host
                stats["host_cells"] += int((table >= HOST).sum())
                stats["host_s"] += time.perf_counter() - t0
                tables.append(res.verdicts)
            else:
                tables.append(table)

        try:
            for start in range(0, max(n, 1), tile):
                sl = slice(start, min(start + tile, n))
                chunk = resources[sl]
                nv = len(chunk)
                t0 = time.perf_counter()
                with global_profiler.phase(PHASE_ENCODE), \
                        global_tracer.span("scan_encode", parent=scan_ctx,
                                           tile=nv):
                    padded = list(chunk) + [{} for _ in range(tile - nv)]
                    ops = None
                    if operations:
                        ops = list(operations[sl]) + [""] * (tile - nv)
                    batch, _ = self.encode(padded, namespace_labels, ops)
                enc_dt = time.perf_counter() - t0
                stats["encode_s"] += enc_dt
                if not pending:
                    # no tile in flight while this one encoded: the
                    # device sat idle waiting on the host — feed
                    # starvation (with tiles in flight the encode hides
                    # behind device time and costs nothing)
                    global_starvation.record(starved_s=enc_dt)
                # async sharded put then dispatch: the H2D copy of tile
                # k+1 overlaps the device compute of tiles k, k-1, ...
                with global_profiler.phase(PHASE_DISPATCH), \
                        global_tracer.span("scan_dispatch", parent=scan_ctx,
                                           tile=nv):
                    verdicts, _ = self._step(self.put(batch))
                pending.append((verdicts, sl, nv))
                stats["tiles"] += 1
                while len(pending) > max(in_flight, 1):
                    drain()
            while pending:
                drain()
        except BaseException as e:
            scan_span.set_status("error", f"{type(e).__name__}: {e}")
            raise
        finally:
            scan_span.attributes["tiles"] = stats["tiles"]
            global_tracer.end_span(scan_span)
        # phase timings land in metrics too (SURVEY §5: emit the
        # per-phase costs scan_stream collects), exemplar-linked to the
        # scan's trace so a slow bucket names the trace that caused it
        ex = {"trace_id": scan_ctx.trace_id}
        global_registry.scan_encode_seconds.observe(stats["encode_s"], exemplar=ex)
        global_registry.scan_device_seconds.observe(stats["device_s"], exemplar=ex)
        global_registry.scan_host_seconds.observe(stats["host_s"], exemplar=ex)

        from ..tpu.engine import ScanResult

        total = np.concatenate(tables, axis=1) if tables else np.zeros(
            (len(self.cps.rules if eng else self.cps.device_programs), 0), dtype=np.int32)
        rules = ([(e.policy_name, e.rule_name) for e in self.cps.rules]
                 if eng is not None
                 else [(p.policy_name, p.rule_name) for p in self.cps.device_programs])
        return ScanResult(verdicts=total, rules=rules), stats

    def step_jitted(self):
        return self._step
