"""Sharded batch evaluation over a jax.sharding.Mesh.

The scan workload is data-parallel over resources: every batch lane has
a leading N axis, the compiled program is elementwise across it, and
per-rule verdict counts are the only cross-device reduction (XLA lowers
the sum over the sharded axis to an ICI all-reduce / reduce-scatter).
This mirrors how the reference scales scans — sharding the resource
keyspace across workers and replicas (SURVEY §2.7) — except the shards
are TPU cores on one mesh instead of goroutine pools.

Policies are replicated (they are compile-time constants baked into the
program); resources shard. For multi-host, the same program runs under
jax.distributed with the mesh spanning hosts — DCN carries only the
final counts, ICI the within-slice reductions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api.policy import ClusterPolicy
from ..tpu.compiler import CompiledPolicySet, compile_policy_set
from ..tpu.evaluator import batch_to_device, build_program
from ..tpu.flatten import EncodeConfig, encode_resources
from ..tpu.metadata import encode_metadata


def make_mesh(devices: Optional[Sequence] = None, axis: str = "data") -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


class ShardedScanner:
    """Compile once, evaluate resource batches sharded across a mesh.

    The jitted step returns (verdicts, counts): the (rules, N) verdict
    table sharded over N, plus per-(rule, verdict-class) totals reduced
    across devices — the scan-service summary used for report rollups.
    """

    NUM_CLASSES = 6

    def __init__(
        self,
        policies: Sequence[ClusterPolicy],
        mesh: Optional[Mesh] = None,
        encode_cfg: Optional[EncodeConfig] = None,
    ):
        self.cps: CompiledPolicySet = compile_policy_set(policies, encode_cfg)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = self.mesh.axis_names[0]
        self._raw_fn = build_program(
            self.cps.device_programs, self.cps.encode_cfg.max_instances
        )
        data_sharding = NamedSharding(self.mesh, P(self.axis))
        repl = NamedSharding(self.mesh, P())

        def step(batch: Dict[str, jnp.ndarray]):
            verdicts = self._raw_fn(batch)  # (rules, N)
            counts = jnp.stack(
                [(verdicts == c).sum(axis=1) for c in range(self.NUM_CLASSES)],
                axis=-1,
            )  # (rules, classes) — cross-device reduction over the N shard
            return verdicts, counts

        self._step = jax.jit(
            step,
            in_shardings=({k: data_sharding for k in self._batch_keys()},),
            out_shardings=(NamedSharding(self.mesh, P(None, self.axis)), repl),
        )

    def _batch_keys(self):
        # all batch lanes lead with N; enumerate from a tiny probe encode
        rows = encode_resources([{}], self.cps.encode_cfg, ())
        meta = encode_metadata([{}])
        return list(batch_to_device(rows, meta).keys())

    @property
    def n_devices(self) -> int:
        return self.mesh.size

    def pad(self, n: int) -> int:
        d = self.n_devices
        return ((n + d - 1) // d) * d

    def encode(self, resources, namespace_labels=None, operations=None):
        n = len(resources)
        padded = self.pad(max(n, 1))
        res = list(resources) + [{} for _ in range(padded - n)]
        ops = (list(operations) + [""] * (padded - n)) if operations else None
        rows = encode_resources(res, self.cps.encode_cfg, self.cps.byte_paths,
                                self.cps.key_byte_paths)
        meta = encode_metadata(res, namespace_labels, ops)
        return batch_to_device(rows, meta), n

    def scan_device(self, resources, namespace_labels=None, operations=None) -> Tuple[np.ndarray, np.ndarray]:
        """Device layer only: (verdicts (device_rules, n), counts).
        Verdicts may contain HOST(5) for resources exceeding encode
        caps, and host-fallback rules are absent — use scan() for the
        complete, resolved result."""
        batch, n = self.encode(resources, namespace_labels, operations)
        verdicts, counts = self._step(batch)
        return np.asarray(verdicts)[:, :n], np.asarray(counts)

    def scan(self, resources, namespace_labels=None, operations=None):
        """Complete ScanResult over ALL rules: device verdicts merged
        with scalar-engine completions (host rules + capped resources) —
        HOST never escapes."""
        from ..tpu.engine import TpuEngine

        device_table, _ = self.scan_device(resources, namespace_labels, operations)
        eng = TpuEngine.from_compiled(self.cps)
        return eng.assemble(device_table, resources, namespace_labels, operations)

    def step_jitted(self):
        return self._step
