"""Bundled policy library (the charts/kyverno-policies equivalent).

`load_pss_policies()` returns the 18-policy Pod Security Standards set
(11 baseline, 6 restricted, 1 supplementary) used by the benchmark
configs (BASELINE.json) and the CLI smoke path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import yaml

from ..api.policy import ClusterPolicy

_PSS_DIR = os.path.join(os.path.dirname(__file__), "pss")


def load_policy_file(path: str) -> List[ClusterPolicy]:
    with open(path) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    return [ClusterPolicy.from_dict(d) for d in docs]


def load_pss_policies(subset: Optional[str] = None) -> List[ClusterPolicy]:
    """subset: None for all, or a filename prefix filter."""
    out: List[ClusterPolicy] = []
    for name in sorted(os.listdir(_PSS_DIR)):
        if not name.endswith(".yaml"):
            continue
        if subset and not name.startswith(subset):
            continue
        out.extend(load_policy_file(os.path.join(_PSS_DIR, name)))
    return out
