"""Policy lifecycle services: autogen, loading, cache, validation."""
