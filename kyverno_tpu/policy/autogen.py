"""Autogen — rewrite Pod rules for the seven pod controllers.

Re-implementation of pkg/autogen (autogen.go:236 ComputeRules,
rule.go:73 generateRule, rule.go:308 updateGenRuleByte): Pod-targeted
rules gain `autogen-<name>` variants whose patterns are wrapped under
`spec.template` (and `spec.jobTemplate.spec.template` for CronJob),
with JMESPath references in deny conditions / preconditions / messages
rewritten from `request.object.spec` to the template-shifted paths.

Controller selection follows the reference exactly: the
`pod-policies.kyverno.io/autogen-controllers` annotation filters the
supported set; rules with names, selectors, annotations, or non-Pod
kinds in any match/exclude block disable autogen for the whole spec
(autogen.go:31 checkAutogenSupport).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional, Tuple

from ..api.policy import ClusterPolicy, Rule

AUTOGEN_ANNOTATION = "pod-policies.kyverno.io/autogen-controllers"
POD_CONTROLLERS = [
    "DaemonSet", "Deployment", "Job", "StatefulSet",
    "ReplicaSet", "ReplicationController", "CronJob",
]
_NON_CRON = [c for c in POD_CONTROLLERS if c != "CronJob"]
_CONTROLLER_SET = set(POD_CONTROLLERS) | {"Pod"}


def _is_kind_other_than_pod(kinds: List[str]) -> bool:
    return len(kinds) > 1 and "Pod" in kinds


def _block_supported(needed: List[bool], block: Dict[str, Any]) -> bool:
    """checkAutogenSupport (autogen.go:31) over one ResourceDescription."""
    rd = block or {}
    if rd.get("name") or rd.get("names") or rd.get("selector") is not None \
            or rd.get("annotations") is not None or _is_kind_other_than_pod(rd.get("kinds") or []):
        return False
    if any(k in _CONTROLLER_SET for k in (rd.get("kinds") or [])):
        needed[0] = True
    return True


def can_auto_gen(spec: Dict[str, Any]) -> Tuple[bool, str]:
    """Port of CanAutoGen (autogen.go:68)."""
    needed = [False]
    for rule in spec.get("rules") or []:
        mutate = rule.get("mutate") or {}
        if mutate.get("patchesJson6902") or rule.get("generate") is not None:
            return False, "none"
        for fe in mutate.get("foreach") or []:
            if fe.get("patchesJson6902"):
                return False, "none"
        for block in (rule.get("match"), rule.get("exclude")):
            block = block or {}
            if not _block_supported(needed, block.get("resources") or {}):
                return False, ""
            for rf in (block.get("any") or []) + (block.get("all") or []):
                if not _block_supported(needed, rf.get("resources") or {}):
                    return False, ""
    if not needed[0]:
        return False, ""
    return True, ",".join(POD_CONTROLLERS)


def _rewrite_refs(rule_dict: Dict[str, Any], kind: str) -> Dict[str, Any]:
    """updateGenRuleByte (rule.go:308): string-level JMESPath shifting."""
    s = json.dumps(rule_dict)
    if kind == "Pod":
        pairs = [
            ("request.object.spec", "request.object.spec.template.spec"),
            ("request.oldObject.spec", "request.oldObject.spec.template.spec"),
            ("request.object.metadata", "request.object.spec.template.metadata"),
            ("request.oldObject.metadata", "request.oldObject.spec.template.metadata"),
        ]
    else:  # Cronjob
        pairs = [
            ("request.object.spec", "request.object.spec.jobTemplate.spec.template.spec"),
            ("request.oldObject.spec", "request.oldObject.spec.jobTemplate.spec.template.spec"),
            ("request.object.metadata", "request.object.spec.jobTemplate.spec.template.metadata"),
            ("request.oldObject.metadata", "request.oldObject.spec.jobTemplate.spec.template.metadata"),
        ]
    for old, new in pairs:
        s = s.replace(old, new)
    return json.loads(s)


def _shift_message_refs(value: str, shift: str, pivot: str) -> str:
    """FindAndShiftReferences (vars.go:474): $() references in validate
    messages get the template shift inserted after the pivot segment."""
    from ..engine.variables import REGEX_REFERENCES

    for m in list(REGEX_REFERENCES.finditer(value or "")):
        old_ref = m.group(0)
        ref = old_ref
        initial = ref[:2] == "$("
        if not initial:
            ref = ref[1:]
        p = pivot
        idx = ref.find(p)
        if p == "anyPattern":
            rule_index = ref[idx + len(p) + 1:].split("/")[0]
            p = p + "/" + rule_index
        shifted = ref.replace(p, p + "/" + shift)
        replacement = ("" if initial else old_ref[0]) + shifted
        value = value.replace(old_ref, replacement, 1)
    return value


def _autogen_name(prefix: str, name: str) -> str:
    out = f"{prefix}-{name}"
    return out[:63]


def _replace_kinds(block: Optional[Dict[str, Any]], kinds: List[str],
                   match_pod_only: bool, is_exclude: bool) -> None:
    """Overwrite Kinds with the controller list (rule.go:81-95,223)."""
    if not block:
        return
    if block.get("any"):
        for rf in block["any"]:
            rd = rf.get("resources") or {}
            if (not match_pod_only) or "Pod" in (rd.get("kinds") or []):
                rd["kinds"] = list(kinds)
    elif block.get("all"):
        for rf in block["all"]:
            rd = rf.get("resources") or {}
            if (not match_pod_only) or "Pod" in (rd.get("kinds") or []):
                rd["kinds"] = list(kinds)
    else:
        rd = block.setdefault("resources", {})
        if is_exclude:
            if rd.get("kinds"):
                rd["kinds"] = list(kinds)
        else:
            rd["kinds"] = list(kinds)


def _wrap(tpl_key: str, value: Any) -> Dict[str, Any]:
    return {"spec": {tpl_key: value}}


def _generate_rule(name: str, rule: Dict[str, Any], tpl_key: str, shift: str,
                   kinds: List[str], match_pod_only: bool) -> Optional[Dict[str, Any]]:
    """generateRule (rule.go:73) over the raw rule dict."""
    rule = copy.deepcopy(rule)
    rule["name"] = name
    _replace_kinds(rule.get("match"), kinds, match_pod_only, is_exclude=False)
    _replace_kinds(rule.get("exclude"), kinds, match_pod_only, is_exclude=True)

    mutate = rule.get("mutate") or {}
    if mutate.get("patchStrategicMerge") is not None:
        rule["mutate"] = {"patchStrategicMerge": _wrap(tpl_key, mutate["patchStrategicMerge"])}
        return rule
    if mutate.get("foreach"):
        out = []
        for fe in mutate["foreach"]:
            nfe = {k: v for k, v in fe.items()
                   if k in ("list", "context", "preconditions")}
            nfe["patchStrategicMerge"] = _wrap(tpl_key, fe.get("patchStrategicMerge"))
            out.append(nfe)
        rule["mutate"] = {"foreach": out}
        return rule

    validate = rule.get("validate") or {}
    if validate.get("pattern") is not None:
        rule["validate"] = {
            "message": _shift_message_refs(validate.get("message", ""), shift, "pattern"),
            "pattern": _wrap(tpl_key, validate["pattern"]),
        }
        return rule
    if validate.get("deny") is not None:
        rule["validate"] = {
            "message": _shift_message_refs(validate.get("message", ""), shift, "deny"),
            "deny": validate["deny"],
        }
        return rule
    if validate.get("podSecurity") is not None:
        rule["validate"] = {
            "message": _shift_message_refs(validate.get("message", ""), shift, "podSecurity"),
            "podSecurity": copy.deepcopy(validate["podSecurity"]),
        }
        return rule
    if validate.get("anyPattern") is not None:
        rule["validate"] = {
            "message": _shift_message_refs(validate.get("message", ""), shift, "anyPattern"),
            "anyPattern": [_wrap(tpl_key, p) for p in validate["anyPattern"]],
        }
        return rule
    if validate.get("foreach"):
        rule["validate"] = {
            "message": _shift_message_refs(validate.get("message", ""), shift, "pattern"),
            "foreach": copy.deepcopy(validate["foreach"]),
        }
        return rule
    if rule.get("verifyImages"):
        return rule
    if validate.get("cel") is not None:
        return rule
    return None


def _kinds_of(block: Optional[Dict[str, Any]]) -> List[str]:
    block = block or {}
    kinds = list((block.get("resources") or {}).get("kinds") or [])
    for rf in (block.get("any") or []) + (block.get("all") or []):
        kinds.extend((rf.get("resources") or {}).get("kinds") or [])
    return kinds


def _rule_for_controllers(rule: Dict[str, Any], controllers: str) -> Optional[Dict[str, Any]]:
    """generateRuleForControllers (rule.go:233)."""
    if rule.get("name", "").startswith("autogen-") or not controllers:
        return None
    match_kinds = _kinds_of(rule.get("match"))
    exclude_kinds = _kinds_of(rule.get("exclude"))
    if "Pod" not in match_kinds or (exclude_kinds and "Pod" not in exclude_kinds):
        return None
    if controllers == "all":
        controllers = ",".join(_NON_CRON)
    else:
        validated = [c for c in controllers.split(",") if c in _NON_CRON]
        if validated:
            controllers = ",".join(validated)
    kinds = [c for c in controllers.split(",") if c]
    if not kinds:
        return None
    return _generate_rule(_autogen_name("autogen", rule["name"]), rule,
                          "template", "spec/template", kinds, match_pod_only=True)


def _cronjob_rule(rule: Dict[str, Any], controllers: str) -> Optional[Dict[str, Any]]:
    """generateCronJobRule (rule.go:286)."""
    if "CronJob" not in controllers and "all" not in controllers:
        return None
    base = _rule_for_controllers(rule, controllers)
    if base is None:
        return None
    return _generate_rule(_autogen_name("autogen-cronjob", rule["name"]), base,
                          "jobTemplate", "spec/jobTemplate/spec/template",
                          ["CronJob"], match_pod_only=False)


def compute_rule_dicts(policy_dict: Dict[str, Any]) -> List[Dict[str, Any]]:
    """ComputeRules (autogen.go:236) over raw dicts: original rules plus
    generated controller variants."""
    spec = policy_dict.get("spec") or {}
    rules = list(spec.get("rules") or [])
    apply_autogen, desired = can_auto_gen(spec)
    annotations = (policy_dict.get("metadata") or {}).get("annotations") or {}
    # key PRESENCE matters: an explicitly empty annotation disables
    # autogen (autogen.go:247 `ok` check), absence means "all supported"
    if AUTOGEN_ANNOTATION in annotations and apply_autogen:
        actual = annotations[AUTOGEN_ANNOTATION]
    else:
        actual = desired
    if not apply_autogen or actual == "none":
        return rules
    strip = ",".join(c for c in actual.split(",") if c != "CronJob") \
        if actual != "all" else actual
    gen: List[Dict[str, Any]] = []
    for rule in rules:
        g = _rule_for_controllers(rule, strip)
        if g is not None:
            gen.append(_rewrite_refs(g, "Pod"))
        c = _cronjob_rule(rule, actual)
        if c is not None:
            gen.append(_rewrite_refs(c, "Cronjob"))
    if not gen:
        return rules
    return rules + gen


def compute_rules(policy: ClusterPolicy) -> List[Rule]:
    return [Rule.from_dict(r) for r in compute_rule_dicts(policy.raw)]


def expand_policy(policy: ClusterPolicy) -> ClusterPolicy:
    """Return a policy whose spec.rules include the autogen variants."""
    raw = copy.deepcopy(policy.raw)
    raw.setdefault("spec", {})["rules"] = compute_rule_dicts(raw)
    return ClusterPolicy.from_dict(raw)
