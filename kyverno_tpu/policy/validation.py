"""Policy object validation (pkg/validation/policy/validate.go).

Validates policies at admission/load time: structural rules (unique
rule names, exactly one rule type, non-empty match), the variable
whitelist with background-mode safety (background policies may not use
admission-request variables, background.go), and pattern sanity
(anchors on scalar leaves, operator spelling). Returns a list of
error strings; empty means valid. Warnings are returned separately.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Set, Tuple

from ..api.policy import ClusterPolicy
from ..engine.anchor import parse as parse_anchor
from ..engine.variables import REGEX_VARIABLES

# allowed_vars (pkg/validation/policy/validate.go ValidateVariables):
# everything the engine seeds plus rule context entry names
_ALLOWED_PREFIXES = (
    "request.", "element", "elementIndex", "@", "images", "image",
    "serviceAccountName", "serviceAccountNamespace", "target.",
    "globalContext.",
)
# background policies cannot see admission request data (background.go)
_BACKGROUND_FORBIDDEN = re.compile(
    r"^request\.(userInfo|roles|clusterRoles)\b")


def _iter_variables(tree: Any):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _iter_variables(k)
            yield from _iter_variables(v)
    elif isinstance(tree, list):
        for v in tree:
            yield from _iter_variables(v)
    elif isinstance(tree, str):
        for m in REGEX_VARIABLES.finditer(tree):
            yield m.group(2)[2:-2].strip()


def _rule_types(rule: Dict[str, Any]) -> List[str]:
    out = []
    for key in ("validate", "mutate", "generate", "verifyImages"):
        if rule.get(key) is not None:
            out.append(key)
    return out


def _validate_body_types(v: Dict[str, Any]) -> List[str]:
    bodies = [k for k in ("pattern", "anyPattern", "deny", "foreach",
                          "podSecurity", "cel", "manifests") if v.get(k) is not None]
    errs = []
    if len(bodies) == 0:
        errs.append("validate rule requires one of pattern/anyPattern/deny/"
                    "foreach/podSecurity/cel/manifests")
    if len(bodies) > 1:
        errs.append(f"validate rule may declare only one body, found {bodies}")
    return errs


def _check_match_block(rule: Dict[str, Any]) -> List[str]:
    match = rule.get("match") or {}
    blocks = []
    if match.get("any"):
        blocks = [rf.get("resources") or {} for rf in match["any"]]
    elif match.get("all"):
        blocks = [rf.get("resources") or {} for rf in match["all"]]
    else:
        blocks = [match.get("resources") or {}]
    errs = []
    user_blocks = [match] + list(match.get("any") or []) + list(match.get("all") or [])
    has_user = any(b.get("subjects") or b.get("roles") or b.get("clusterRoles")
                   for b in user_blocks)
    if not has_user and all(not any(b.get(f) for f in (
            "kinds", "name", "names", "namespaces", "annotations",
            "selector", "namespaceSelector", "operations")) for b in blocks):
        errs.append(f"rule {rule.get('name')!r}: match block cannot be empty")
    # subject kinds (user_info_types.go:38 ValidateSubjects) — match
    # and exclude both carry UserInfo, at top level and per any/all
    exclude = rule.get("exclude") or {}
    for b in user_blocks + [exclude] + list(exclude.get("any") or []) \
            + list(exclude.get("all") or []):
        for subject in b.get("subjects") or []:
            kind = subject.get("kind", "")
            if not subject.get("name"):
                errs.append(f"rule {rule.get('name')!r}: subject name is "
                            f"required")
            if kind not in ("User", "Group", "ServiceAccount"):
                errs.append(f"rule {rule.get('name')!r}: subject kind must be "
                            f"'User', 'Group', or 'ServiceAccount', got {kind!r}")
            elif kind == "ServiceAccount" and not subject.get("namespace"):
                errs.append(f"rule {rule.get('name')!r}: namespace is required "
                            f"when subject kind is ServiceAccount")
    return errs


# bare kinds that only exist as subresources (discovery would report
# them with a parent resource; validate.go:1462 rejects them for
# background scans — there is no parent object to scan)
_SUBRESOURCE_ONLY_KINDS = frozenset({
    "Scale", "Eviction", "PodExecOptions", "PodAttachOptions",
    "PodPortForwardOptions", "PodProxyOptions", "NodeProxyOptions",
    "ServiceProxyOptions", "TokenRequest", "Binding",
    "LocalSubjectAccessReview",
})


def _check_background_subresources(rule: Dict[str, Any],
                                   errs: List[str]) -> None:
    """validate.go:1447 checkForScanSubresource: background scans
    cannot target subresources."""
    from ..utils.kube import parse_kind_selector

    match = rule.get("match") or {}
    blocks = ([match.get("resources") or {}]
              + [rf.get("resources") or {} for rf in match.get("any") or []]
              + [rf.get("resources") or {} for rf in match.get("all") or []])
    for b in blocks:
        for k in b.get("kinds") or []:
            _, _, kind, subresource = parse_kind_selector(str(k))
            if subresource or kind in _SUBRESOURCE_ONLY_KINDS:
                errs.append(f"background scan enabled with subresource {k}")


def _check_pattern_anchors(pattern: Any, path: str, errs: List[str]) -> None:
    if isinstance(pattern, dict):
        for k, v in pattern.items():
            a = parse_anchor(str(k))
            if a is not None and a.modifier == "+":
                errs.append(f"addIfNotPresent anchor +() is a mutate anchor, "
                            f"not valid in validate patterns (at {path}/{k})")
            _check_pattern_anchors(v, f"{path}/{k}", errs)
    elif isinstance(pattern, list):
        for i, v in enumerate(pattern):
            _check_pattern_anchors(v, f"{path}/{i}", errs)


# api/kyverno/v1/common_types.go:278-297 — the 18 condition operators
_CONDITION_OPERATORS = {
    "Equal", "Equals", "NotEqual", "NotEquals", "In", "AnyIn", "AllIn",
    "NotIn", "AnyNotIn", "AllNotIn", "GreaterThanOrEquals", "GreaterThan",
    "LessThanOrEquals", "LessThan", "DurationGreaterThanOrEquals",
    "DurationGreaterThan", "DurationLessThanOrEquals", "DurationLessThan",
}

_REQUEST_OPERATIONS = {"CREATE", "UPDATE", "DELETE", "CONNECT"}

# exactly one source per context entry (validateRuleContext,
# validate.go:1184)
_CONTEXT_SOURCES = ("configMap", "apiCall", "imageRegistry", "variable",
                    "globalReference")

# names the engine seeds itself; context entries may not shadow them
_RESERVED_CONTEXT_NAMES = {"request", "element", "elementIndex", "images",
                           "image", "serviceAccountName",
                           "serviceAccountNamespace", "target"}

_JSON_PATCH_OPS = {"add", "remove", "replace", "move", "copy", "test"}


def _iter_conditions(node: Any):
    """Yield {key, operator, value} condition dicts from any/all trees
    or legacy flat lists."""
    if isinstance(node, dict):
        if "operator" in node or "key" in node:
            yield node
        for sub in (node.get("any"), node.get("all")):
            if isinstance(sub, list):
                for c in sub:
                    yield from _iter_conditions(c)
    elif isinstance(node, list):
        for c in node:
            yield from _iter_conditions(c)


def _check_conditions(node: Any, where: str, errs: List[str]) -> None:
    """validateConditions (validate.go:1004): operator must be one of
    the 18; {{request.operation}} values constrained to the four
    admission operations (validate.go:1139)."""
    for c in _iter_conditions(node):
        op = c.get("operator", "")
        if op and op not in _CONDITION_OPERATORS:
            errs.append(f"{where}: invalid condition operator {op!r}")
        key = c.get("key")
        if isinstance(key, str) and key.replace(" ", "") == "{{request.operation}}":
            values = c.get("value")
            values = values if isinstance(values, list) else [values]
            for v in values:
                if isinstance(v, str) and v.startswith("{{") and v.endswith("}}"):
                    continue
                if v not in _REQUEST_OPERATIONS:
                    errs.append(
                        f"{where}: unknown value {v!r} for "
                        f"{{{{request.operation}}}}; allowed: "
                        f"[CREATE, UPDATE, DELETE, CONNECT]")


def _check_context_entries(rule: Dict[str, Any], errs: List[str]) -> None:
    """validateRuleContext (validate.go:1184): one source per entry,
    no reserved names, apiCall/variable field sanity."""
    name = rule.get("name") or ""
    for entry in rule.get("context") or []:
        ename = entry.get("name") or ""
        if not ename:
            errs.append(f"rule {name!r}: context entry without a name")
        if ename in _RESERVED_CONTEXT_NAMES:
            errs.append(f"rule {name!r}: context entry name {ename!r} "
                        f"shadows a reserved variable")
        sources = [s for s in _CONTEXT_SOURCES if entry.get(s) is not None]
        if len(sources) != 1:
            errs.append(
                f"rule {name!r}: context entry {ename!r} requires exactly "
                f"one of {'/'.join(_CONTEXT_SOURCES)}, found {sources or 'none'}")
            continue
        if sources == ["variable"]:
            var = entry["variable"] or {}
            if var.get("value") is None and not var.get("jmesPath"):
                errs.append(f"rule {name!r}: variable context entry "
                            f"{ename!r} requires value or jmesPath")
        if sources == ["apiCall"]:
            call = entry["apiCall"] or {}
            if not call.get("urlPath") and not (call.get("service") or {}).get("url"):
                errs.append(f"rule {name!r}: apiCall context entry "
                            f"{ename!r} requires urlPath or service.url")
            if call.get("urlPath") and (call.get("service") or {}).get("url"):
                errs.append(f"rule {name!r}: apiCall context entry "
                            f"{ename!r} cannot have both urlPath and service")


def _check_mutate_existing(raw_spec: Dict[str, Any], rule: Dict[str, Any],
                           errs: List[str]) -> None:
    """Mutate-existing validation (pkg/validation/policy):
    - mutateExistingOnPolicyUpdate requires every mutate rule to
      declare targets (there is no admission object to mutate);
    - target selectors may not reference {{ target.* }} — the target
      is not resolved until after selection."""
    name = rule.get("name") or ""
    mutate = rule.get("mutate") or {}
    if not mutate:
        return
    targets = mutate.get("targets")
    if raw_spec.get("mutateExistingOnPolicyUpdate") and not targets:
        errs.append(f"rule {name!r}: mutateExistingOnPolicyUpdate requires "
                    f"mutate.targets")
    for i, t in enumerate(targets or []):
        for field_name in ("name", "namespace", "apiVersion", "kind"):
            val = t.get(field_name)
            if isinstance(val, str) and ("{{target." in val.replace(" ", "")):
                errs.append(
                    f"rule {name!r}: mutate.targets[{i}].{field_name} may "
                    f"not reference target.* variables (unresolved at "
                    f"target selection)")


def _check_json_patch(rule: Dict[str, Any], errs: List[str]) -> None:
    """validateJSONPatch (validate.go:87): op/path shape, no variables
    in the path section (validate.go:590)."""
    import yaml as _yaml

    name = rule.get("name") or ""
    mutate = rule.get("mutate") or {}
    patch = mutate.get("patchesJson6902")
    if not patch:
        return
    try:
        ops = _yaml.safe_load(patch) if isinstance(patch, str) else patch
    except _yaml.YAMLError as e:
        errs.append(f"rule {name!r}: invalid patchesJson6902: {e}")
        return
    if not isinstance(ops, list):
        errs.append(f"rule {name!r}: patchesJson6902 must be a list")
        return
    for i, op in enumerate(ops):
        if not isinstance(op, dict):
            errs.append(f"rule {name!r}: patchesJson6902[{i}] must be a map")
            continue
        if op.get("op") not in _JSON_PATCH_OPS:
            errs.append(f"rule {name!r}: patchesJson6902[{i}] has invalid "
                        f"op {op.get('op')!r}")
        path = op.get("path", "")
        if not isinstance(path, str) or not path.startswith("/"):
            errs.append(f"rule {name!r}: patchesJson6902[{i}] path must "
                        f"start with '/'")
        elif REGEX_VARIABLES.search(path):
            errs.append(f"rule {name!r}: variables are not allowed in "
                        f"patchesJson6902 path")


def _check_forbidden_variables(rule: Dict[str, Any], errs: List[str]) -> None:
    """ruleForbiddenSectionsHaveVariables (validate.go:528): match,
    exclude and verifyImages imageReferences may not contain
    variables."""
    name = rule.get("name") or ""
    for section in ("match", "exclude"):
        for var in _iter_variables(rule.get(section) or {}):
            if var.strip().startswith("element"):
                continue
            errs.append(f"rule {name!r}: variables are not allowed in the "
                        f"{section} section ({{{{{var}}}}})")
            break
    for iv in rule.get("verifyImages") or []:
        for ref in (iv.get("imageReferences") or []):
            if isinstance(ref, str) and REGEX_VARIABLES.search(ref):
                errs.append(f"rule {name!r}: variables are not allowed in "
                            f"image reference {ref!r}")


def _check_generate(rule: Dict[str, Any], errs: List[str],
                    auth_checker=None) -> None:
    """generate-rule structure + CanIGenerate permission seam
    (validate.go generate checks, pkg/auth CanI)."""
    name = rule.get("name") or ""
    gen = rule.get("generate")
    if gen is None:
        return
    has_data = gen.get("data") is not None
    has_clone = bool(gen.get("clone")) or bool(gen.get("cloneList"))
    if has_data == has_clone:
        errs.append(f"rule {name!r}: generate requires exactly one of "
                    f"data or clone/cloneList")
    if not gen.get("kind") and not gen.get("cloneList"):
        # cloneList carries its kinds inside the block
        errs.append(f"rule {name!r}: generate requires kind")
    if not gen.get("name") and not gen.get("cloneList"):
        errs.append(f"rule {name!r}: generate requires name")
    clone = gen.get("clone") or {}
    if clone and not clone.get("name"):
        errs.append(f"rule {name!r}: generate clone requires name")
    if auth_checker is not None and gen.get("kind"):
        for verb in ("create", "update", "delete", "get"):
            if not auth_checker(verb, gen.get("kind", ""),
                                gen.get("namespace", "")):
                errs.append(
                    f"rule {name!r}: controller lacks {verb!r} permission "
                    f"for generated kind {gen.get('kind')!r} "
                    f"(CanIGenerate)")
                break


def _check_kinds_resolvable(policy: ClusterPolicy, rule: Dict[str, Any],
                            kind_resolver, errors: List[str]) -> None:
    """validKinds (validate.go:1384,1404): every non-wildcard kind must
    resolve against discovery, and a namespaced Policy cannot match
    cluster-scoped resources. `kind_resolver(selector)` returns
    'Namespaced' | 'Cluster' | None (unknown)."""
    namespaced = policy.raw.get("kind") == "Policy"
    kinds: List[str] = []
    for block_name in ("match", "exclude"):
        block = rule.get(block_name) or {}
        kinds.extend((block.get("resources") or {}).get("kinds") or [])
        for rf in (block.get("any") or []) + (block.get("all") or []):
            kinds.extend((rf.get("resources") or {}).get("kinds") or [])
    from ..utils.kube import parse_kind_selector

    for k in kinds:
        if parse_kind_selector(k)[2] == "*":
            continue  # wildcard KINDS bypass discovery (validateKinds);
            # 'Foo/*' still resolves Foo
        scope = kind_resolver(k)
        if scope is None:
            errors.append(f"unable to convert GVK to GVR for kinds {k}")
        elif namespaced and scope == "Cluster":
            errors.append(f"namespaced policy cannot match cluster-scoped "
                          f"resource kind {k}")


def validate_policy(policy: ClusterPolicy,
                    extra_allowed: Tuple[str, ...] = (),
                    auth_checker=None,
                    kind_resolver=None) -> Tuple[List[str], List[str]]:
    """Returns (errors, warnings)."""
    errors: List[str] = []
    warnings: List[str] = []
    raw = policy.raw
    if not policy.name:
        errors.append("policy has no name")
    spec = raw.get("spec") or {}
    rules = spec.get("rules") or []
    if not rules:
        errors.append("policy has no rules")
    seen: Set[str] = set()
    background = spec.get("background", True)
    admission = spec.get("admission", True)
    # spec-level gates (pkg/validation/policy/validate.go:211-218,
    # api/kyverno/v1/spec_types.go:339)
    if not admission and not background:
        errors.append("disabling both admission and background processing "
                      "is not allowed")
    if not admission and any(
            r.get("mutate") or r.get("generate") or r.get("verifyImages")
            for r in rules):
        errors.append("disabling admission processing is only allowed with "
                      "validation policies")
    timeout = spec.get("webhookTimeoutSeconds")
    if timeout is not None and not (isinstance(timeout, int)
                                    and not isinstance(timeout, bool)
                                    and 1 <= timeout <= 30):
        errors.append("the timeout value must be between 1 and 30 seconds")
    for rule in rules:
        name = rule.get("name") or ""
        if not name:
            errors.append("rule without a name")
        if name in seen:
            errors.append(f"duplicate rule name {name!r}")
        seen.add(name)
        if len(name) > 63:
            errors.append(f"rule name {name!r} exceeds 63 characters")
        types = _rule_types(rule)
        if len(types) != 1:
            errors.append(
                f"rule {name!r} must define exactly one of validate/mutate/"
                f"generate/verifyImages, found {types or 'none'}")
        errors.extend(_check_match_block(rule))
        if kind_resolver is not None:
            _check_kinds_resolvable(policy, rule, kind_resolver, errors)
        # validate.go:1459: subresource kinds only invalid for VALIDATE
        # rules under background scanning
        if background and rule.get("validate") is not None:
            _check_background_subresources(rule, errors)
        # rule-level context entries and preconditions run before any
        # target binds, so {{target.*}} references there can never
        # resolve (validate.go:46 allowed-variable split for targets)
        rule_scope = {"context": rule.get("context") or [],
                      "preconditions": rule.get("preconditions")}
        if "{{target." in json.dumps(rule_scope, default=str).replace(" ", ""):
            errors.append(f"rule {name!r}: target.* variables are only "
                          f"allowed inside mutate.targets")
        _check_context_entries(rule, errors)
        _check_json_patch(rule, errors)
        _check_mutate_existing(spec, rule, errors)
        _check_forbidden_variables(rule, errors)
        _check_generate(rule, errors, auth_checker)
        _check_conditions(rule.get("preconditions"),
                          f"rule {name!r} preconditions", errors)
        v = rule.get("validate")
        if v is not None:
            errors.extend(f"rule {name!r}: {e}" for e in _validate_body_types(v))
            if v.get("pattern") is not None:
                _check_pattern_anchors(v["pattern"], "pattern", errors)
            for p in v.get("anyPattern") or []:
                _check_pattern_anchors(p, "anyPattern", errors)
            deny = v.get("deny") or {}
            _check_conditions(deny.get("conditions"),
                              f"rule {name!r} deny conditions", errors)
            for fe in v.get("foreach") or []:
                _check_conditions((fe.get("deny") or {}).get("conditions"),
                                  f"rule {name!r} foreach deny", errors)
                _check_conditions(fe.get("preconditions"),
                                  f"rule {name!r} foreach preconditions",
                                  errors)
        # variable whitelist
        context_names = tuple(
            (c.get("name") or "") for c in (rule.get("context") or []))
        allowed = _ALLOWED_PREFIXES + context_names + extra_allowed
        for var in set(_iter_variables(rule)):
            base = var.split("|")[0].strip()
            if base.startswith("\"") or base.startswith("'"):
                continue
            root = re.split(r"[.\[(]", base, 1)[0]
            if not any(base.startswith(p) or root == p.rstrip(".")
                       for p in allowed):
                warnings.append(
                    f"rule {name!r}: variable {{{{{var}}}}} is not in the "
                    f"allowed list and will fail policy admission")
            if background and _BACKGROUND_FORBIDDEN.match(base):
                errors.append(
                    f"rule {name!r}: background policies cannot reference "
                    f"admission request data ({{{{{var}}}}}); set "
                    f"spec.background=false")
    return errors, warnings
