"""Policy object validation (pkg/validation/policy/validate.go).

Validates policies at admission/load time: structural rules (unique
rule names, exactly one rule type, non-empty match), the variable
whitelist with background-mode safety (background policies may not use
admission-request variables, background.go), and pattern sanity
(anchors on scalar leaves, operator spelling). Returns a list of
error strings; empty means valid. Warnings are returned separately.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Set, Tuple

from ..api.policy import ClusterPolicy
from ..engine.anchor import parse as parse_anchor
from ..engine.variables import REGEX_VARIABLES

# allowed_vars (pkg/validation/policy/validate.go ValidateVariables):
# everything the engine seeds plus rule context entry names
_ALLOWED_PREFIXES = (
    "request.", "element", "elementIndex", "@", "images", "image",
    "serviceAccountName", "serviceAccountNamespace", "target.",
    "globalContext.",
)
# background policies cannot see admission request data (background.go)
_BACKGROUND_FORBIDDEN = re.compile(
    r"^request\.(userInfo|roles|clusterRoles)\b")


def _iter_variables(tree: Any):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _iter_variables(k)
            yield from _iter_variables(v)
    elif isinstance(tree, list):
        for v in tree:
            yield from _iter_variables(v)
    elif isinstance(tree, str):
        for m in REGEX_VARIABLES.finditer(tree):
            yield m.group(2)[2:-2].strip()


def _rule_types(rule: Dict[str, Any]) -> List[str]:
    out = []
    for key in ("validate", "mutate", "generate", "verifyImages"):
        if rule.get(key) is not None:
            out.append(key)
    return out


def _validate_body_types(v: Dict[str, Any]) -> List[str]:
    bodies = [k for k in ("pattern", "anyPattern", "deny", "foreach",
                          "podSecurity", "cel", "manifests") if v.get(k) is not None]
    errs = []
    if len(bodies) == 0:
        errs.append("validate rule requires one of pattern/anyPattern/deny/"
                    "foreach/podSecurity/cel/manifests")
    if len(bodies) > 1:
        errs.append(f"validate rule may declare only one body, found {bodies}")
    return errs


def _check_match_block(rule: Dict[str, Any]) -> List[str]:
    match = rule.get("match") or {}
    blocks = []
    if match.get("any"):
        blocks = [rf.get("resources") or {} for rf in match["any"]]
    elif match.get("all"):
        blocks = [rf.get("resources") or {} for rf in match["all"]]
    else:
        blocks = [match.get("resources") or {}]
    errs = []
    user_blocks = [match] + list(match.get("any") or []) + list(match.get("all") or [])
    has_user = any(b.get("subjects") or b.get("roles") or b.get("clusterRoles")
                   for b in user_blocks)
    if not has_user and all(not any(b.get(f) for f in (
            "kinds", "name", "names", "namespaces", "annotations",
            "selector", "namespaceSelector", "operations")) for b in blocks):
        errs.append(f"rule {rule.get('name')!r}: match block cannot be empty")
    return errs


def _check_pattern_anchors(pattern: Any, path: str, errs: List[str]) -> None:
    if isinstance(pattern, dict):
        for k, v in pattern.items():
            a = parse_anchor(str(k))
            if a is not None and a.modifier == "+":
                errs.append(f"addIfNotPresent anchor +() is a mutate anchor, "
                            f"not valid in validate patterns (at {path}/{k})")
            _check_pattern_anchors(v, f"{path}/{k}", errs)
    elif isinstance(pattern, list):
        for i, v in enumerate(pattern):
            _check_pattern_anchors(v, f"{path}/{i}", errs)


def validate_policy(policy: ClusterPolicy,
                    extra_allowed: Tuple[str, ...] = ()) -> Tuple[List[str], List[str]]:
    """Returns (errors, warnings)."""
    errors: List[str] = []
    warnings: List[str] = []
    raw = policy.raw
    if not policy.name:
        errors.append("policy has no name")
    spec = raw.get("spec") or {}
    rules = spec.get("rules") or []
    if not rules:
        errors.append("policy has no rules")
    seen: Set[str] = set()
    background = spec.get("background", True)
    for rule in rules:
        name = rule.get("name") or ""
        if not name:
            errors.append("rule without a name")
        if name in seen:
            errors.append(f"duplicate rule name {name!r}")
        seen.add(name)
        if len(name) > 63:
            errors.append(f"rule name {name!r} exceeds 63 characters")
        types = _rule_types(rule)
        if len(types) != 1:
            errors.append(
                f"rule {name!r} must define exactly one of validate/mutate/"
                f"generate/verifyImages, found {types or 'none'}")
        errors.extend(_check_match_block(rule))
        v = rule.get("validate")
        if v is not None:
            errors.extend(f"rule {name!r}: {e}" for e in _validate_body_types(v))
            if v.get("pattern") is not None:
                _check_pattern_anchors(v["pattern"], "pattern", errors)
            for p in v.get("anyPattern") or []:
                _check_pattern_anchors(p, "anyPattern", errors)
        # variable whitelist
        context_names = tuple(
            (c.get("name") or "") for c in (rule.get("context") or []))
        allowed = _ALLOWED_PREFIXES + context_names + extra_allowed
        for var in set(_iter_variables(rule)):
            base = var.split("|")[0].strip()
            if base.startswith("\"") or base.startswith("'"):
                continue
            root = re.split(r"[.\[(]", base, 1)[0]
            if not any(base.startswith(p) or root == p.rstrip(".")
                       for p in allowed):
                warnings.append(
                    f"rule {name!r}: variable {{{{{var}}}}} is not in the "
                    f"allowed list and will fail policy admission")
            if background and _BACKGROUND_FORBIDDEN.match(base):
                errors.append(
                    f"rule {name!r}: background policies cannot reference "
                    f"admission request data ({{{{{var}}}}}); set "
                    f"spec.background=false")
    return errors, warnings
