"""Pod Security Standards evaluation for ``validate.podSecurity`` rules.

Native implementation of the PSS controls the reference gets from
k8s.io/pod-security-admission (wrapped in pkg/pss/evaluate.go):
``level: baseline|restricted`` (+ ``version``), with Kyverno
``exclude`` entries suppressing individual control failures.

Each violation records WHERE it came from — the canonical
restrictedField path for its container section and the offending
values — because exclusions are field-scoped: an entry with
``restrictedField``/``values`` only exempts violations at that exact
field whose offending values are all covered by the listed values
(pkg/pss/evaluate.go ExemptProfile); ``images`` globs further scope
container-level exclusions to matching images.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..engine.response import RULE_TYPE_VALIDATION, RuleResponse
from ..utils import wildcard

# (control, detail, violating image ("" = pod-level),
#  restrictedField path, offending values)
Violation = Tuple[str, str, str, str, List[Any]]


def _pod_spec(resource: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    kind = resource.get("kind")
    if kind == "Pod":
        return resource.get("spec") or {}
    # controller kinds carry a pod template
    spec = resource.get("spec") or {}
    template = spec.get("template") or {}
    if kind == "CronJob":
        template = ((spec.get("jobTemplate") or {}).get("spec") or {}).get("template") or {}
    return template.get("spec") if template else None


def _sectioned(spec: Dict[str, Any]) -> List[Tuple[str, Dict[str, Any]]]:
    """(section, container) pairs — the section names the
    restrictedField root (spec.containers[*] vs spec.initContainers[*]
    vs spec.ephemeralContainers[*])."""
    out = []
    for key in ("initContainers", "containers", "ephemeralContainers"):
        out.extend((key, c) for c in spec.get(key) or [])
    return out


# --------------------------------------------------------------------------
# baseline controls

# pod-security-admission capabilities_baseline.go capabilities_allowed:
# baseline is an ALLOWLIST — adding anything beyond it (including
# unknown capability names) is a violation
_BASELINE_ALLOWED_CAPS = {
    "AUDIT_WRITE", "CHOWN", "DAC_OVERRIDE", "FOWNER", "FSETID", "KILL",
    "MKNOD", "NET_BIND_SERVICE", "SETFCAP", "SETGID", "SETPCAP",
    "SETUID", "SYS_CHROOT",
}

_ALLOWED_VOLUME_TYPES_RESTRICTED = {
    "configMap", "csi", "downwardAPI", "emptyDir", "ephemeral",
    "persistentVolumeClaim", "projected", "secret",
}


def _check_host_namespaces(spec, sections) -> List[Violation]:
    out = []
    for fieldname in ("hostNetwork", "hostPID", "hostIPC"):
        if spec.get(fieldname):
            out.append(("Host Namespaces", f"{fieldname} is not allowed", "",
                        f"spec.{fieldname}", [True]))
    return out


def _check_privileged(spec, sections) -> List[Violation]:
    return [
        ("Privileged Containers", f"container {c.get('name')!r} is privileged",
         c.get("image", ""),
         f"spec.{sec}[*].securityContext.privileged", [True])
        for sec, c in sections
        if (c.get("securityContext") or {}).get("privileged")
    ]


def _check_capabilities_baseline(spec, sections) -> List[Violation]:
    out = []
    for sec, c in sections:
        caps = ((c.get("securityContext") or {}).get("capabilities") or {}).get("add") or []
        bad = [cap for cap in caps if cap not in _BASELINE_ALLOWED_CAPS]
        if bad:
            out.append(("Capabilities", f"container {c.get('name')!r} adds {sorted(bad)}",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.capabilities.add", bad))
    return out


def _check_host_path(spec, sections) -> List[Violation]:
    # map-valued restricted fields expose the map KEYS as bad values
    # (conformance: exclusion restrictedField spec.volumes[*].hostPath
    # with values ["path"] exempts a {path: ...} hostPath volume)
    return [
        ("HostPath Volumes", f"volume {v.get('name')!r} uses hostPath", "",
         "spec.volumes[*].hostPath", sorted((v.get("hostPath") or {}).keys()) or [""])
        for v in spec.get("volumes") or []
        if "hostPath" in v
    ]


def _check_host_ports(spec, sections) -> List[Violation]:
    out = []
    for sec, c in sections:
        for p in c.get("ports") or []:
            if p.get("hostPort"):
                out.append(("Host Ports",
                            f"container {c.get('name')!r} uses hostPort {p['hostPort']}",
                            c.get("image", ""),
                            f"spec.{sec}[*].ports[*].hostPort", [p["hostPort"]]))
    return out


def _check_selinux(spec, sections) -> List[Violation]:
    allowed = {"", "container_t", "container_init_t", "container_kvm_t", "container_engine_t"}
    out = []
    for sec, scope in [("", spec)] + list(sections):
        img = scope.get("image", "") if scope is not spec else ""
        root = (f"spec.{sec}[*].securityContext" if scope is not spec
                else "spec.securityContext")
        opts = (scope.get("securityContext") or {}).get("seLinuxOptions") or {}
        if opts.get("type") and opts["type"] not in allowed:
            out.append(("SELinux", f"seLinuxOptions.type {opts['type']!r} is not allowed",
                        img, f"{root}.seLinuxOptions.type", [opts["type"]]))
        for f in ("user", "role"):
            if opts.get(f):
                out.append(("SELinux", f"seLinuxOptions {f} may not be set",
                            img, f"{root}.seLinuxOptions.{f}", [opts[f]]))
    return out


def _check_proc_mount(spec, sections) -> List[Violation]:
    # "default" is accepted case-insensitively (conformance: psa/
    # test-exclusion-procmount admits procMount: default)
    return [
        ("/proc Mount Type", f"container {c.get('name')!r} uses procMount={sc['procMount']}",
         c.get("image", ""),
         f"spec.{sec}[*].securityContext.procMount", [sc["procMount"]])
        for sec, c in sections
        for sc in [c.get("securityContext") or {}]
        if sc.get("procMount") is not None
        and str(sc["procMount"]).lower() != "default"
    ]


def _check_seccomp_baseline(spec, sections) -> List[Violation]:
    # baseline (v1.19+ seccompProfile_baseline): IF set, the type must
    # be RuntimeDefault or Localhost — unknown types are forbidden too
    out = []
    prof = ((spec.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
    if prof is not None and prof not in ("RuntimeDefault", "Localhost"):
        out.append(("Seccomp", f"pod: seccompProfile.type {prof!r} is not allowed",
                    "", "spec.securityContext.seccompProfile.type", [prof]))
    for sec, c in sections:
        prof = ((c.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
        if prof is not None and prof not in ("RuntimeDefault", "Localhost"):
            out.append(("Seccomp",
                        f"{c.get('name')}: seccompProfile.type {prof!r} is not allowed",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.seccompProfile.type",
                        [prof]))
    return out


def _check_sysctls(spec, sections) -> List[Violation]:
    safe = {
        "kernel.shm_rmid_forced", "net.ipv4.ip_local_port_range",
        "net.ipv4.ip_unprivileged_port_start", "net.ipv4.tcp_syncookies",
        "net.ipv4.ping_group_range", "net.ipv4.tcp_keepalive_time",
        "net.ipv4.tcp_fin_timeout", "net.ipv4.tcp_keepalive_intvl",
        "net.ipv4.tcp_keepalive_probes",
    }
    out = []
    for s in (spec.get("securityContext") or {}).get("sysctls") or []:
        if s.get("name") not in safe:
            out.append(("Sysctls", f"sysctl {s.get('name')!r} is not allowed", "",
                        "spec.securityContext.sysctls[*].name", [s.get("name")]))
    return out


def _check_windows_host_process(spec, sections) -> List[Violation]:
    out = []
    opts = ((spec.get("securityContext") or {}).get("windowsOptions") or {})
    if opts.get("hostProcess"):
        out.append(("HostProcess", "pod: hostProcess is not allowed", "",
                    "spec.securityContext.windowsOptions.hostProcess", [True]))
    for sec, c in sections:
        opts = ((c.get("securityContext") or {}).get("windowsOptions") or {})
        if opts.get("hostProcess"):
            out.append(("HostProcess", f"{c.get('name')}: hostProcess is not allowed",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.windowsOptions.hostProcess",
                        [True]))
    return out


# --------------------------------------------------------------------------
# restricted controls


def _check_volume_types(spec, sections) -> List[Violation]:
    out = []
    for v in spec.get("volumes") or []:
        kinds = set(v.keys()) - {"name"}
        bad = kinds - _ALLOWED_VOLUME_TYPES_RESTRICTED
        for t in sorted(bad):
            # one violation per restricted type, keyed by its field
            # with the type's map keys as bad values (see hostPath)
            keys = sorted(v[t].keys()) if isinstance(v[t], dict) else [v[t]]
            out.append(("Volume Types", f"volume {v.get('name')!r} uses {t}",
                        "", f"spec.volumes[*].{t}", keys or [""]))
    return out


def _check_privilege_escalation(spec, sections) -> List[Violation]:
    return [
        ("Privilege Escalation",
         f"container {c.get('name')!r} must set allowPrivilegeEscalation=false",
         c.get("image", ""),
         f"spec.{sec}[*].securityContext.allowPrivilegeEscalation",
         [(c.get("securityContext") or {}).get("allowPrivilegeEscalation")])
        for sec, c in sections
        if (c.get("securityContext") or {}).get("allowPrivilegeEscalation") is not False
    ]


def _check_run_as_non_root(spec, sections) -> List[Violation]:
    pod_level = (spec.get("securityContext") or {}).get("runAsNonRoot")
    out = []
    for sec, c in sections:
        c_level = (c.get("securityContext") or {}).get("runAsNonRoot")
        effective = c_level if c_level is not None else pod_level
        if effective is not True:
            # the violating field is the one actually set (container
            # overrides pod; neither set -> the container field)
            if c_level is None and pod_level is not None:
                field = "spec.securityContext.runAsNonRoot"
            else:
                field = f"spec.{sec}[*].securityContext.runAsNonRoot"
            out.append(("Running as Non-root",
                        f"container {c.get('name')!r} must set runAsNonRoot=true",
                        c.get("image", ""), field, [effective]))
    return out


def _check_run_as_user(spec, sections) -> List[Violation]:
    out = []
    if (spec.get("securityContext") or {}).get("runAsUser") == 0:
        out.append(("Running as Non-root user", "pod runAsUser=0 is not allowed",
                    "", "spec.securityContext.runAsUser", [0]))
    for sec, c in sections:
        if (c.get("securityContext") or {}).get("runAsUser") == 0:
            out.append(("Running as Non-root user",
                        f"container {c.get('name')!r} runAsUser=0",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.runAsUser", [0]))
    return out


def _check_seccomp_restricted(spec, sections) -> List[Violation]:
    pod_prof = ((spec.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
    out = []
    for sec, c in sections:
        prof = ((c.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
        effective = prof if prof is not None else pod_prof
        if effective not in ("RuntimeDefault", "Localhost"):
            if prof is None and pod_prof is not None:
                field = "spec.securityContext.seccompProfile.type"
            else:
                field = f"spec.{sec}[*].securityContext.seccompProfile.type"
            out.append(("Seccomp", f"container {c.get('name')!r} must set seccompProfile",
                        c.get("image", ""), field, [effective]))
    return out


def _check_capabilities_restricted(spec, sections) -> List[Violation]:
    out = []
    for sec, c in sections:
        caps = (c.get("securityContext") or {}).get("capabilities") or {}
        drops = caps.get("drop") or []
        if "ALL" not in drops:
            out.append(("Capabilities", f"container {c.get('name')!r} must drop ALL",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.capabilities.drop", drops))
        adds = sorted(set(caps.get("add") or []) - {"NET_BIND_SERVICE"})
        if adds:
            out.append(("Capabilities", f"container {c.get('name')!r} adds {adds}",
                        c.get("image", ""),
                        f"spec.{sec}[*].securityContext.capabilities.add", adds))
    return out


# (control title, check fn, upstream CheckResult.ID, upstream
# ForbiddenReason) — ids/reasons per pod-security-admission policy/
# checks and the reference's PSS_controls_to_check_id
# (pkg/pss/utils/mapping.go:45)
_BASELINE_CHECKS: List[Tuple[str, Callable, str, str]] = [
    ("Host Namespaces", _check_host_namespaces,
     "hostNamespaces", "host namespaces"),
    ("Privileged Containers", _check_privileged,
     "privileged", "privileged"),
    ("Capabilities", _check_capabilities_baseline,
     "capabilities_baseline", "non-default capabilities"),
    ("HostPath Volumes", _check_host_path,
     "hostPathVolumes", "hostPath volumes"),
    ("Host Ports", _check_host_ports, "hostPorts", "hostPort"),
    ("SELinux", _check_selinux, "seLinuxOptions", "seLinuxOptions"),
    ("/proc Mount Type", _check_proc_mount, "procMount", "procMount"),
    ("Seccomp", _check_seccomp_baseline,
     "seccompProfile_baseline", "seccompProfile"),
    ("Sysctls", _check_sysctls, "sysctls", "forbidden sysctls"),
    ("HostProcess", _check_windows_host_process,
     "windowsHostProcess", "hostProcess"),
]

_RESTRICTED_CHECKS: List[Tuple[str, Callable, str, str]] = _BASELINE_CHECKS + [
    ("Volume Types", _check_volume_types,
     "restrictedVolumes", "restricted volume types"),
    ("Privilege Escalation", _check_privilege_escalation,
     "allowPrivilegeEscalation", "allowPrivilegeEscalation != false"),
    ("Running as Non-root", _check_run_as_non_root,
     "runAsNonRoot", "runAsNonRoot != true"),
    ("Running as Non-root user", _check_run_as_user,
     "runAsUser", "runAsUser=0"),
    ("Seccomp", _check_seccomp_restricted,
     "seccompProfile_restricted", "seccompProfile"),
    ("Capabilities", _check_capabilities_restricted,
     "capabilities_restricted", "unrestricted capabilities"),
]


def evaluate_pss(level: str, resource: Dict[str, Any]) -> List[Violation]:
    """Run the control set for ``level`` over a pod-bearing resource."""
    return [v for v, _, _ in evaluate_pss_detailed(level, resource)]


def evaluate_pss_detailed(
        level: str, resource: Dict[str, Any]
) -> List[Tuple[Violation, str, str]]:
    """(violation, check id, upstream forbidden reason) triples — the
    id/reason pair feeds report properties and the reference-format
    failure message (evaluate.go:331 FormatChecksPrint)."""
    spec = _pod_spec(resource)
    if spec is None:
        return []
    sections = _sectioned(spec)
    checks = _RESTRICTED_CHECKS if level == "restricted" else _BASELINE_CHECKS
    out: List[Tuple[Violation, str, str]] = []
    for _, check, check_id, reason in checks:
        out.extend((v, check_id, reason) for v in check(spec, sections))
    return out


def _stringify(v: Any) -> str:
    if v is True:
        return "true"
    if v is False:
        return "false"
    if v is None:
        return "null"
    return str(v)


def _excluded(violation: Violation, resource: Dict[str, Any],
              excludes: List[Dict[str, Any]]) -> bool:
    """pkg/pss ExemptProfile semantics: controlName must match; images
    globs scope container-level exclusions to matching images (a
    glob-bearing exclusion never exempts pod-level violations); a
    restrictedField-bearing exclusion only exempts violations at that
    exact field whose offending values are ALL covered by the listed
    values (wildcards allowed)."""
    control, _, image, field, values = violation
    for ex in excludes:
        if ex.get("controlName") != control:
            continue
        globs = ex.get("images") or []
        if globs and not (image and any(wildcard.match(g, image) for g in globs)):
            continue
        rf = ex.get("restrictedField")
        if rf and rf != field:
            continue
        if ex.get("values") is not None:
            # values apply even without a restrictedField: every
            # offending value must be covered (evaluate.go:104-113)
            exvals = [str(x) for x in ex["values"]]
            if not all(any(wildcard.match(p, _stringify(v)) for p in exvals)
                       for v in values):
                continue
        return True
    return False


def _indexed_field(resource: Dict[str, Any], field_path: str,
                   detail: str) -> str:
    """Replace the '[*]' section wildcard in a violation's
    restrictedField with the offending container's index (upstream
    field errors are index-addressed: spec.containers[0]....)."""
    if "[*]" not in field_path:
        return field_path
    m = re.search(r"'([^']+)'", detail)
    spec = _pod_spec(resource) or {}
    section = field_path.split(".")[1].split("[")[0]
    containers = spec.get(section)
    idx = 0
    if m and isinstance(containers, list):
        for i, c in enumerate(containers):
            if isinstance(c, dict) and c.get("name") == m.group(1):
                idx = i
                break
    return field_path.replace("[*]", f"[{idx}]", 1)


def validate_pod_security(rule_name: str, validation, resource: Dict[str, Any],
                          extra_exclusions=None) -> RuleResponse:
    """Entry point used by the engine for validate.podSecurity rules.
    ``extra_exclusions``: podSecurity controls contributed by matching
    PolicyExceptions (validate_pss.go HasPodSecurity branch)."""
    ps = validation.pod_security or {}
    level = ps.get("level", "baseline")
    version = ps.get("version", "latest")
    excludes = (ps.get("exclude") or []) + list(extra_exclusions or [])
    detailed = [(v, cid, reason)
                for v, cid, reason in evaluate_pss_detailed(level, resource)
                if not _excluded(v, resource, excludes)]
    if not detailed:
        return RuleResponse.rule_pass(rule_name, RULE_TYPE_VALIDATION, "")
    # reference failure format (validate_pss.go:107 + evaluate.go:331
    # FormatChecksPrint): one block per failed upstream check, field
    # errors index-addressed; properties carry the failed check ids
    # (report rows assert on both)
    groups: Dict[str, List[str]] = {}
    reasons: Dict[str, str] = {}
    for v, cid, reason in detailed:
        _, det, _, fpath, _ = v
        fpath = _indexed_field(resource, fpath, det)
        err = "Required value" if fpath.endswith(".capabilities.drop") \
            else "Forbidden"
        groups.setdefault(cid, []).append(f"{fpath}: {err}")
        reasons[cid] = reason
    msg = (f"Validation rule '{rule_name}' failed. It violates PodSecurity "
           f'"{level}:{version}": ')
    for cid, errs in groups.items():
        msg += (f"\n(Forbidden reason: {reasons[cid]}, "
                f"field error list: [{', '.join(errs)}])")
    return RuleResponse.rule_fail(
        rule_name, RULE_TYPE_VALIDATION, msg,
        properties={"controls": ",".join(sorted(groups)),
                    "standard": level, "version": version},
    )
