"""Pod Security Standards evaluation for ``validate.podSecurity`` rules.

Native implementation of the PSS controls the reference gets from
k8s.io/pod-security-admission (wrapped in pkg/pss/evaluate.go):
``level: baseline|restricted`` (+ ``version``), with Kyverno
``exclude`` entries (controlName + optional images globs) suppressing
individual control failures.

Controls implemented mirror the upstream check registry; each returns
the list of violating (control, detail) pairs for a pod spec.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..engine.response import RULE_TYPE_VALIDATION, RuleResponse
from ..utils import wildcard

Violation = Tuple[str, str, str]  # (control, detail, violating image; "" = pod-level)


def _pod_spec(resource: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    kind = resource.get("kind")
    if kind == "Pod":
        return resource.get("spec") or {}
    # controller kinds carry a pod template
    spec = resource.get("spec") or {}
    template = spec.get("template") or {}
    if kind == "CronJob":
        template = ((spec.get("jobTemplate") or {}).get("spec") or {}).get("template") or {}
    return template.get("spec") if template else None


def _all_containers(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    out = []
    for key in ("initContainers", "containers", "ephemeralContainers"):
        out.extend(spec.get(key) or [])
    return out


# --------------------------------------------------------------------------
# baseline controls

_BASELINE_DISALLOWED_CAPS = {
    "AUDIT_CONTROL", "AUDIT_READ", "AUDIT_WRITE", "BLOCK_SUSPEND", "BPF",
    "CHECKPOINT_RESTORE", "DAC_READ_SEARCH", "IPC_LOCK", "IPC_OWNER",
    "LEASE", "LINUX_IMMUTABLE", "MAC_ADMIN", "MAC_OVERRIDE", "MKNOD",
    "NET_ADMIN", "NET_BROADCAST", "NET_RAW", "PERFMON", "SYS_ADMIN",
    "SYS_BOOT", "SYS_MODULE", "SYS_NICE", "SYS_PACCT", "SYS_PTRACE",
    "SYS_RAWIO", "SYS_RESOURCE", "SYS_TIME", "SYS_TTY_CONFIG", "SYSLOG",
    "WAKE_ALARM",
}

_ALLOWED_VOLUME_TYPES_RESTRICTED = {
    "configMap", "csi", "downwardAPI", "emptyDir", "ephemeral",
    "persistentVolumeClaim", "projected", "secret",
}


def _check_host_namespaces(spec, containers) -> List[Violation]:
    out = []
    for fieldname in ("hostNetwork", "hostPID", "hostIPC"):
        if spec.get(fieldname):
            out.append(("Host Namespaces", f"{fieldname} is not allowed", ""))
    return out


def _check_privileged(spec, containers) -> List[Violation]:
    return [
        ("Privileged Containers", f"container {c.get('name')!r} is privileged", c.get("image", ""))
        for c in containers
        if (c.get("securityContext") or {}).get("privileged")
    ]


def _check_capabilities_baseline(spec, containers) -> List[Violation]:
    out = []
    for c in containers:
        caps = ((c.get("securityContext") or {}).get("capabilities") or {}).get("add") or []
        bad = [cap for cap in caps if cap in _BASELINE_DISALLOWED_CAPS or cap == "ALL"]
        if bad:
            out.append(("Capabilities", f"container {c.get('name')!r} adds {sorted(bad)}", c.get("image", "")))
    return out


def _check_host_path(spec, containers) -> List[Violation]:
    return [
        ("HostPath Volumes", f"volume {v.get('name')!r} uses hostPath", "")
        for v in spec.get("volumes") or []
        if "hostPath" in v
    ]


def _check_host_ports(spec, containers) -> List[Violation]:
    out = []
    for c in containers:
        for p in c.get("ports") or []:
            if p.get("hostPort"):
                out.append(("Host Ports", f"container {c.get('name')!r} uses hostPort {p['hostPort']}", c.get("image", "")))
    return out


def _check_selinux(spec, containers) -> List[Violation]:
    allowed = {"", "container_t", "container_init_t", "container_kvm_t", "container_engine_t"}
    out = []
    for scope in [spec] + containers:
        img = scope.get("image", "") if scope is not spec else ""
        opts = (scope.get("securityContext") or {}).get("seLinuxOptions") or {}
        if opts.get("type") and opts["type"] not in allowed:
            out.append(("SELinux", f"seLinuxOptions.type {opts['type']!r} is not allowed", img))
        if opts.get("user") or opts.get("role"):
            out.append(("SELinux", "seLinuxOptions user/role may not be set", img))
    return out


def _check_proc_mount(spec, containers) -> List[Violation]:
    return [
        ("/proc Mount Type", f"container {c.get('name')!r} uses procMount={sc['procMount']}", c.get("image", ""))
        for c in containers
        for sc in [c.get("securityContext") or {}]
        if sc.get("procMount") not in (None, "Default")
    ]


def _check_seccomp_baseline(spec, containers) -> List[Violation]:
    out = []
    for scope, label in [(spec, "pod")] + [(c, c.get("name")) for c in containers]:
        img = scope.get("image", "") if scope is not spec else ""
        prof = ((scope.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
        if prof == "Unconfined":
            out.append(("Seccomp", f"{label}: seccompProfile.type Unconfined is not allowed", img))
    return out


def _check_sysctls(spec, containers) -> List[Violation]:
    safe = {
        "kernel.shm_rmid_forced", "net.ipv4.ip_local_port_range",
        "net.ipv4.ip_unprivileged_port_start", "net.ipv4.tcp_syncookies",
        "net.ipv4.ping_group_range", "net.ipv4.tcp_keepalive_time",
        "net.ipv4.tcp_fin_timeout", "net.ipv4.tcp_keepalive_intvl",
        "net.ipv4.tcp_keepalive_probes",
    }
    out = []
    for s in (spec.get("securityContext") or {}).get("sysctls") or []:
        if s.get("name") not in safe:
            out.append(("Sysctls", f"sysctl {s.get('name')!r} is not allowed", ""))
    return out


def _check_windows_host_process(spec, containers) -> List[Violation]:
    out = []
    for scope, label in [(spec, "pod")] + [(c, c.get("name")) for c in containers]:
        img = scope.get("image", "") if scope is not spec else ""
        opts = ((scope.get("securityContext") or {}).get("windowsOptions") or {})
        if opts.get("hostProcess"):
            out.append(("HostProcess", f"{label}: hostProcess is not allowed", img))
    return out


# --------------------------------------------------------------------------
# restricted controls


def _check_volume_types(spec, containers) -> List[Violation]:
    out = []
    for v in spec.get("volumes") or []:
        kinds = set(v.keys()) - {"name"}
        bad = kinds - _ALLOWED_VOLUME_TYPES_RESTRICTED
        if bad:
            out.append(("Volume Types", f"volume {v.get('name')!r} uses {sorted(bad)}", ""))
    return out


def _check_privilege_escalation(spec, containers) -> List[Violation]:
    return [
        ("Privilege Escalation", f"container {c.get('name')!r} must set allowPrivilegeEscalation=false", c.get("image", ""))
        for c in containers
        if (c.get("securityContext") or {}).get("allowPrivilegeEscalation") is not False
    ]


def _check_run_as_non_root(spec, containers) -> List[Violation]:
    pod_level = (spec.get("securityContext") or {}).get("runAsNonRoot")
    out = []
    for c in containers:
        c_level = (c.get("securityContext") or {}).get("runAsNonRoot")
        effective = c_level if c_level is not None else pod_level
        if effective is not True:
            out.append(("Running as Non-root", f"container {c.get('name')!r} must set runAsNonRoot=true", c.get("image", "")))
    return out


def _check_run_as_user(spec, containers) -> List[Violation]:
    out = []
    if (spec.get("securityContext") or {}).get("runAsUser") == 0:
        out.append(("Running as Non-root user", "pod runAsUser=0 is not allowed", ""))
    for c in containers:
        if (c.get("securityContext") or {}).get("runAsUser") == 0:
            out.append(("Running as Non-root user", f"container {c.get('name')!r} runAsUser=0", c.get("image", "")))
    return out


def _check_seccomp_restricted(spec, containers) -> List[Violation]:
    pod_prof = ((spec.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
    out = []
    for c in containers:
        prof = ((c.get("securityContext") or {}).get("seccompProfile") or {}).get("type")
        effective = prof if prof is not None else pod_prof
        if effective not in ("RuntimeDefault", "Localhost"):
            out.append(("Seccomp", f"container {c.get('name')!r} must set seccompProfile", c.get("image", "")))
    return out


def _check_capabilities_restricted(spec, containers) -> List[Violation]:
    out = []
    for c in containers:
        caps = (c.get("securityContext") or {}).get("capabilities") or {}
        drops = caps.get("drop") or []
        if "ALL" not in drops:
            out.append(("Capabilities", f"container {c.get('name')!r} must drop ALL", c.get("image", "")))
        adds = set(caps.get("add") or []) - {"NET_BIND_SERVICE"}
        if adds:
            out.append(("Capabilities", f"container {c.get('name')!r} adds {sorted(adds)}", c.get("image", "")))
    return out


_BASELINE_CHECKS: List[Tuple[str, Callable]] = [
    ("Host Namespaces", _check_host_namespaces),
    ("Privileged Containers", _check_privileged),
    ("Capabilities", _check_capabilities_baseline),
    ("HostPath Volumes", _check_host_path),
    ("Host Ports", _check_host_ports),
    ("SELinux", _check_selinux),
    ("/proc Mount Type", _check_proc_mount),
    ("Seccomp", _check_seccomp_baseline),
    ("Sysctls", _check_sysctls),
    ("HostProcess", _check_windows_host_process),
]

_RESTRICTED_CHECKS: List[Tuple[str, Callable]] = _BASELINE_CHECKS + [
    ("Volume Types", _check_volume_types),
    ("Privilege Escalation", _check_privilege_escalation),
    ("Running as Non-root", _check_run_as_non_root),
    ("Running as Non-root user", _check_run_as_user),
    ("Seccomp", _check_seccomp_restricted),
    ("Capabilities", _check_capabilities_restricted),
]


def evaluate_pss(level: str, resource: Dict[str, Any]) -> List[Violation]:
    """Run the control set for ``level`` over a pod-bearing resource."""
    spec = _pod_spec(resource)
    if spec is None:
        return []
    containers = _all_containers(spec)
    checks = _RESTRICTED_CHECKS if level == "restricted" else _BASELINE_CHECKS
    out: List[Violation] = []
    for _, check in checks:
        out.extend(check(spec, containers))
    return out


def _excluded(violation: Violation, resource: Dict[str, Any], excludes: List[Dict[str, Any]]) -> bool:
    """pkg/pss exemptExclusions: an exclusion with image globs exempts
    only violations from containers whose image matches; pod-level
    violations need an exclusion without image globs."""
    control, _, image = violation
    for ex in excludes:
        if ex.get("controlName") != control:
            continue
        globs = ex.get("images") or []
        if not globs:
            return True
        if image and any(wildcard.match(g, image) for g in globs):
            return True
    return False


def validate_pod_security(rule_name: str, validation, resource: Dict[str, Any],
                          extra_exclusions=None) -> RuleResponse:
    """Entry point used by the engine for validate.podSecurity rules.
    ``extra_exclusions``: podSecurity controls contributed by matching
    PolicyExceptions (validate_pss.go HasPodSecurity branch)."""
    ps = validation.pod_security or {}
    level = ps.get("level", "baseline")
    excludes = (ps.get("exclude") or []) + list(extra_exclusions or [])
    violations = [v for v in evaluate_pss(level, resource) if not _excluded(v, resource, excludes)]
    if not violations:
        return RuleResponse.rule_pass(rule_name, RULE_TYPE_VALIDATION, "")
    detail = "; ".join(f"{c}: {d}" for c, d, _ in violations)
    return RuleResponse.rule_fail(
        rule_name, RULE_TYPE_VALIDATION, f"pod security {level!r} checks failed: {detail}"
    )
