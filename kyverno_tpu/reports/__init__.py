"""Crash-consistent incremental policy reports (PAPER.md layers 6-7).

``store.ReportStore`` maintains report state as a delta fold over the
per-resource verdict columns the scanner already produces, journaled
for crash consistency (``journal.py``); ``rebuild()`` is the
bit-identity oracle for every delta path.
"""

from .store import (ReportStore, configure_reports, get_report_store,
                    reports_state, reset_reports)

__all__ = ["ReportStore", "configure_reports", "get_report_store",
           "reports_state", "reset_reports"]
