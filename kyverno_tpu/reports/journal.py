"""Report journal — length-prefixed, checksummed delta log + snapshots.

The report store's crash-consistency substrate. Every fold delta is
framed as::

    u32 payload-length | u32 CRC32(payload) | payload (canonical JSON)

and appended (write + flush, so a SIGKILL'd process loses nothing the
kernel already has). Periodically the store compacts: the full base
row set is written as an atomic snapshot (``.tmp`` + ``os.replace``,
sha256-checksummed — the same validate-or-rebuild-cold ladder as the
mmap columnar store) and the journal resets.

Recovery walks the journal until the FIRST record that fails framing,
CRC, or decode, truncates the file to that good prefix, and counts the
reason on ``kyverno_reports_recoveries_total`` — a torn write degrades
the report to an older consistent state, never a wrong one. Records
whose monotonic ``seq`` is not strictly newer than what the snapshot
(or an earlier record) already covers are duplicate replays — skipped
and counted, so a crash between snapshot-replace and journal-truncate
cannot double-fold a delta.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

_HEADER = struct.Struct("<II")  # payload length, CRC32(payload)

JOURNAL_NAME = "journal.wal"
SNAPSHOT_NAME = "snapshot.json"
SNAPSHOT_VERSION = 1

# recovery-ladder reasons — the label set of
# kyverno_reports_recoveries_total{reason}
REASON_SHORT_HEADER = "short_header"      # trailing bytes < header size
REASON_TRUNCATED = "truncated_record"     # header promises more bytes than exist
REASON_CHECKSUM = "checksum"              # CRC mismatch (bit flip / torn write)
REASON_DECODE = "decode"                  # CRC ok but payload not valid JSON
REASON_DUPLICATE = "duplicate"            # seq already covered (double replay)
REASON_SNAPSHOT = "snapshot"              # snapshot failed validation, cold start
REASON_REPLAY = "replay"                  # unclean shutdown: journal replayed
REASON_APPEND_ERROR = "append_error"      # live append failed; delta not logged


def canonical(obj: Any) -> str:
    """Canonical JSON — the byte-stable serialization digests and
    checksums are computed over."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def frame(payload: bytes, wire: Optional[bytes] = None) -> bytes:
    """Frame one record. Length and CRC always describe ``payload``;
    the bytes actually written are ``wire`` when given — the
    corrupt-fault hook point: a mangled wire payload is exactly what
    the CRC catches at replay."""
    return _HEADER.pack(len(payload), zlib.crc32(payload)) \
        + (payload if wire is None else wire)


def scan_records(data: bytes) -> Tuple[List[Dict[str, Any]], int,
                                       Optional[str]]:
    """Walk framed records -> (docs, good_prefix_bytes, bad_reason).

    Stops at the first record that fails framing/CRC/decode; everything
    before it is the good prefix. ``bad_reason`` is None on a clean
    walk, else the recovery-ladder reason for the failure."""
    docs: List[Dict[str, Any]] = []
    off = 0
    n = len(data)
    while off < n:
        if n - off < _HEADER.size:
            return docs, off, REASON_SHORT_HEADER
        length, crc = _HEADER.unpack_from(data, off)
        start = off + _HEADER.size
        if n - start < length:
            return docs, off, REASON_TRUNCATED
        payload = data[start:start + length]
        if zlib.crc32(payload) != crc:
            return docs, off, REASON_CHECKSUM
        try:
            doc = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return docs, off, REASON_DECODE
        if not isinstance(doc, dict):
            return docs, off, REASON_DECODE
        docs.append(doc)
        off = start + length
    return docs, off, None


def _rows_checksum(seq: int, rows: List[Any]) -> str:
    return hashlib.sha256(canonical([seq, rows]).encode("utf-8")).hexdigest()


def write_snapshot(path: str, seq: int, rows: List[Any]) -> None:
    """Atomic compacted snapshot: serialized to ``.tmp``, fsynced,
    renamed into place — a crash mid-write leaves the previous
    snapshot untouched. Every step routes through the storage shim
    (surface ``reports``) so a full/erroring disk degrades the store
    to memory-only folding instead of raising out of compaction."""
    from ..resilience import storage as st

    body = {"version": SNAPSHOT_VERSION, "seq": seq, "rows": rows,
            "checksum": _rows_checksum(seq, rows)}
    tmp = path + ".tmp"
    try:
        with st.open_truncate(tmp, st.SURFACE_REPORTS) as f:
            st.write_frame(f, canonical(body), st.SURFACE_REPORTS, path=tmp)
            st.fsync(f, st.SURFACE_REPORTS, path=tmp)
        st.atomic_replace(tmp, path, st.SURFACE_REPORTS)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str) -> Optional[Tuple[int, List[Any]]]:
    """-> (seq, rows), or None on ANY validation failure — the
    validate-or-rebuild-cold ladder: a snapshot that fails version,
    shape, or checksum checks is discarded wholesale, never partially
    trusted."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            body = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(body, dict) or body.get("version") != SNAPSHOT_VERSION:
        return None
    seq, rows = body.get("seq"), body.get("rows")
    if not isinstance(seq, int) or not isinstance(rows, list):
        return None
    if body.get("checksum") != _rows_checksum(seq, rows):
        return None
    return seq, rows
