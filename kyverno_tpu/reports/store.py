"""Crash-consistent incremental report store.

PAPER.md layers 6-7 (PolicyReport / ClusterPolicyReport /
EphemeralReport aggregation) re-expressed as a columnar fold: the
engine already produces exact per-resource verdict columns, so report
maintenance is a delta fold keyed by ``(resource sha, policy-set
content key)``:

- an upsert whose ``(sha, ps_key)`` pair is unchanged is ZERO work —
  no journal append, no count updates (``reports_fold_skipped``);
- a changed upsert unfolds the resource's previous rows from the
  derived counts and folds the new ones (``reports_fold_ops``) —
  report cost scales with what moved, never with cluster size;
- a delete unfolds and forgets;
- ``rebuild()`` recomputes the derived counts from the base rows from
  scratch — the bit-identity oracle every delta path is checked
  against (``digest()`` compares the full state canonically).

Crash consistency (journal.py): each delta appends to a
length-prefixed CRC'd journal BEFORE it folds, with periodic compacted
snapshots; recovery replays the good prefix and counts every
degradation on ``kyverno_reports_recoveries_total{reason}``. A fold
that dies midway (fault site ``reports.fold``) degrades to a full
derived-count rebuild from base — slower, never wrong.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..cluster.reports import RESULT_NAMES, PolicyReport, ReportResult
from ..observability.metrics import global_registry
from ..resilience import storage as st
from ..resilience.faults import (SITE_REPORTS_FOLD, SITE_REPORTS_JOURNAL,
                                 global_faults)
from . import journal as jn

# base record: (sha, ps_key, namespace, kind, name, rows) with rows a
# list of [policy, rule, result] triples — plain JSON types only, so a
# journal/snapshot round trip reproduces the in-memory value exactly
# (digest bit-identity across restarts depends on it)
Rec = Tuple[str, str, str, str, str, List[List[str]]]


class ReportStore:
    """Incremental report state: base rows + derived counts, journaled.

    ``directory=None`` runs in-memory (no journal, no snapshots) —
    same fold semantics, no durability."""

    def __init__(self, directory: Optional[str] = None,
                 journal_max_bytes: int = 4 << 20) -> None:
        self.directory = directory
        self.journal_max_bytes = max(4096, int(journal_max_bytes))
        self.metrics = global_registry
        self._lock = threading.Lock()
        # base state: uid -> Rec
        self._rows: Dict[str, Rec] = {}          # guarded-by: _lock
        # derived state, incrementally folded (pruned at zero so a
        # fold/unfold sequence is bit-identical to a fresh rebuild)
        self._ns_counts: Dict[str, Dict[str, int]] = {}      # guarded-by: _lock
        self._policy_counts: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        self._totals: Dict[str, int] = {}        # guarded-by: _lock
        self._seq = 0                            # guarded-by: _lock
        self._journal_fh = None                  # guarded-by: _lock
        self._journal_bytes = 0                  # guarded-by: _lock
        self._heal_compact = False               # guarded-by: _lock
        self.stats = {"recovered_records": 0, "verify_checks": 0,
                      "compactions": 0}          # guarded-by: _lock
        if directory:
            try:
                st.makedirs(directory, st.SURFACE_REPORTS)
            except OSError:
                pass  # degraded at boot: fold in memory, heal by probe
            with self._lock:
                self._load_locked()

    # -- the fold

    def apply(self, uid: str, sha: str, ps_key: str, ns: str, kind: str,
              name: str, rows: Iterable[Sequence[str]]) -> bool:
        """Fold one resource's verdict rows. Returns True when a delta
        was journaled+folded, False on the unchanged zero-work path."""
        norm = [[str(p), str(r), str(s)] for (p, r, s) in rows]
        with self._lock:
            old = self._rows.get(uid)
            if old is not None and old[0] == sha and old[1] == ps_key:
                self.metrics.reports_fold_skipped.inc()
                return False
            new: Rec = (str(sha), str(ps_key), str(ns), str(kind),
                        str(name), norm)
            self._journal_locked({"op": "put", "uid": uid, "sha": new[0],
                                  "ps": new[1], "ns": new[2],
                                  "kind": new[3], "name": new[4],
                                  "rows": norm})
            self._fold_locked(uid, old, new)
            self._maybe_compact_locked()
        return True

    def delete(self, uid: str) -> bool:
        """Unfold and forget a deleted resource's rows."""
        with self._lock:
            old = self._rows.get(uid)
            if old is None:
                return False
            self._journal_locked({"op": "del", "uid": uid})
            self._fold_locked(uid, old, None)
            self._maybe_compact_locked()
        return True

    def _fold_locked(self, uid: str, old: Optional[Rec],
                     new: Optional[Rec]) -> None:
        if new is None:
            self._rows.pop(uid, None)
        else:
            self._rows[uid] = new
        try:
            global_faults.fire(SITE_REPORTS_FOLD, payload=uid)
            if old is not None:
                self._count_locked(old, -1)
            if new is not None:
                self._count_locked(new, +1)
            self.metrics.reports_fold_ops.inc()
        except Exception:
            # the fold died midway: derived counts may be half-updated.
            # Base rows are already correct, so degrade to a full
            # derived rebuild — slower, counted, never a wrong report.
            self._rebuild_derived_locked()
            self.metrics.reports_rebuilds.inc()
        self.metrics.reports_resources.set(float(len(self._rows)))

    def _count_locked(self, rec: Rec, delta: int) -> None:
        ns = rec[2]
        for policy, _rule, result in rec[5]:
            _bump(self._ns_counts, ns, result, delta)
            _bump(self._policy_counts, policy, result, delta)
            v = self._totals.get(result, 0) + delta
            if v:
                self._totals[result] = v
            else:
                self._totals.pop(result, None)

    def _rebuild_derived_locked(self) -> None:
        self._ns_counts = {}
        self._policy_counts = {}
        self._totals = {}
        for rec in self._rows.values():
            self._count_locked(rec, +1)

    # -- the oracle

    def rebuild(self) -> str:
        """From-scratch recompute of derived state from base rows — the
        bit-identity oracle for every delta path. Returns the
        post-rebuild digest."""
        with self._lock:
            self._rebuild_derived_locked()
            self.metrics.reports_rebuilds.inc()
            return self._digest_locked()

    def digest(self) -> str:
        """Canonical sha256 over the ENTIRE report state (base rows +
        derived counts). Two stores with equal digests hold
        bit-identical reports."""
        with self._lock:
            return self._digest_locked()

    def _digest_locked(self) -> str:
        body = {"rows": self._rows, "ns": self._ns_counts,
                "policy": self._policy_counts, "totals": self._totals}
        return hashlib.sha256(jn.canonical(body).encode("utf-8")).hexdigest()

    def verify_rebuild(self) -> bool:
        """Delta-state == rebuild() bit-identity check. On mismatch the
        rebuilt (correct) derived state replaces the drifted one."""
        with self._lock:
            before = self._digest_locked()
            self._rebuild_derived_locked()
            self.stats["verify_checks"] += 1
            return before == self._digest_locked()

    # -- journal + snapshot

    def _journal_locked(self, doc: Dict[str, Any]) -> None:
        self._seq += 1
        doc["seq"] = self._seq
        if not self.directory:
            return
        health = st.storage_health(st.SURFACE_REPORTS)
        if not health.allow():
            # degraded storage, no re-probe due: memory-only folding.
            # The fold stays bit-identical; only durability is lost,
            # and the loss is counted like any other failed append.
            self.metrics.reports_recoveries.inc(
                {"reason": jn.REASON_APPEND_ERROR})
            return
        was_degraded = health.degraded
        jpath = os.path.join(self.directory, jn.JOURNAL_NAME)
        try:
            if self._journal_fh is None:
                # a boot-time or mid-run open failure left us without a
                # WAL: each granted probe retries the open itself
                self._journal_fh = st.open_append(jpath, st.SURFACE_REPORTS,
                                                  binary=True)
                self._journal_bytes = self._journal_fh.tell()
            global_faults.fire(SITE_REPORTS_JOURNAL,
                               payload=str(doc.get("uid", "")))
            text = jn.canonical(doc)
            payload = text.encode("utf-8")
            # corrupt-fault hook: the length/CRC header still describes
            # the TRUE payload, so a mangled wire record is exactly the
            # torn/bit-flipped write the replay ladder must truncate at
            wire_text = global_faults.corrupt(SITE_REPORTS_JOURNAL, text)
            wire = payload if wire_text is text \
                else str(wire_text or "").encode("utf-8")
            rec = jn.frame(payload, wire=wire)
            st.write_frame(self._journal_fh, rec, st.SURFACE_REPORTS,
                           path=jpath, flush=True)
            self._journal_bytes += len(rec)
            self.metrics.reports_journal_records.inc()
            self.metrics.reports_journal_bytes.set(float(self._journal_bytes))
        except Exception:
            # a failed append must not take report maintenance down:
            # the delta still folds in memory and the LOSS is counted —
            # after a restart the state is older, never wrong. (An
            # OSError also degraded the reports surface via the shim.)
            self.metrics.reports_recoveries.inc(
                {"reason": jn.REASON_APPEND_ERROR})
            return
        if was_degraded and not health.degraded:
            # the probe append landed: the surface just healed. The
            # on-disk journal has a hole (drops while degraded), so
            # durability is re-established by an immediate compaction.
            # Deferred to after the caller's fold: compacting HERE
            # would snapshot state without this very delta and then
            # truncate its journal record — losing the healing row.
            self._heal_compact = True

    def _maybe_compact_locked(self) -> None:
        if self._heal_compact:
            # full in-memory state (healing delta now folded) to
            # snapshot, journal truncated: durability re-established
            self._heal_compact = False
            self._compact_locked()
            return
        if self._journal_fh is not None \
                and self._journal_bytes > self.journal_max_bytes \
                and not st.storage_health(st.SURFACE_REPORTS).degraded:
            # while degraded, compaction would just hammer the sick
            # disk — the journal-append probes own the heal path, and
            # healing compacts immediately anyway
            self._compact_locked()

    def _compact_locked(self) -> None:
        if self._journal_fh is None or not self.directory:
            return
        rows = [[uid, rec[0], rec[1], rec[2], rec[3], rec[4], rec[5]]
                for uid, rec in sorted(self._rows.items())]
        try:
            jn.write_snapshot(os.path.join(self.directory, jn.SNAPSHOT_NAME),
                              self._seq, rows)
        except OSError:
            return  # disk trouble: keep journaling, retry next tick
        # snapshot is durable first, THEN the journal resets — a crash
        # between the two leaves duplicate-seq records the replay skips
        self._journal_fh.seek(0)
        self._journal_fh.truncate()
        self._journal_bytes = 0
        self.stats["compactions"] += 1
        self.metrics.reports_snapshots.inc()
        self.metrics.reports_journal_bytes.set(0.0)

    def _load_locked(self) -> None:
        snap_path = os.path.join(self.directory, jn.SNAPSHOT_NAME)
        jpath = os.path.join(self.directory, jn.JOURNAL_NAME)
        if os.path.exists(snap_path):
            loaded = jn.load_snapshot(snap_path)
            if loaded is None:
                # validate-or-rebuild-cold: a bad snapshot discards
                # BOTH files (journal deltas without their base are not
                # a report) and starts empty — degraded, never wrong;
                # the next scan tick repopulates from live verdicts
                self.metrics.reports_recoveries.inc(
                    {"reason": jn.REASON_SNAPSHOT})
                for stale in (snap_path, jpath):
                    try:
                        os.remove(stale)
                    except OSError:
                        pass
            else:
                self._seq, rows = loaded
                for row in rows:
                    try:
                        uid, sha, ps, ns, kind, name, rws = row
                        self._rows[str(uid)] = (
                            str(sha), str(ps), str(ns), str(kind), str(name),
                            [[str(c) for c in r] for r in rws])
                    except (TypeError, ValueError):
                        continue
        data = b""
        if os.path.exists(jpath):
            try:
                with open(jpath, "rb") as f:
                    data = f.read()
            except OSError:
                data = b""
        docs, good, reason = jn.scan_records(data)
        if reason is not None:
            self.metrics.reports_recoveries.inc({"reason": reason})
            try:
                with open(jpath, "r+b") as f:
                    f.truncate(good)
            except OSError:
                pass
            data = data[:good]
        last = self._seq
        replayed = 0
        for doc in docs:
            seq = doc.get("seq")
            if not isinstance(seq, int) or seq <= last:
                self.metrics.reports_recoveries.inc(
                    {"reason": jn.REASON_DUPLICATE})
                continue
            last = seq
            if self._replay_doc_locked(doc):
                replayed += 1
        self._seq = last
        self._rebuild_derived_locked()
        if replayed:
            # journal records at boot = the previous process died
            # without a clean close: the recovery itself is counted
            self.metrics.reports_recoveries.inc({"reason": jn.REASON_REPLAY})
            self.stats["recovered_records"] += replayed
        try:
            self._journal_fh = st.open_append(jpath, st.SURFACE_REPORTS,
                                              binary=True)
        except OSError:
            self._journal_fh = None  # degraded: appends probe the re-open
        self._journal_bytes = len(data)
        self.metrics.reports_journal_bytes.set(float(self._journal_bytes))
        self.metrics.reports_resources.set(float(len(self._rows)))

    def _replay_doc_locked(self, doc: Dict[str, Any]) -> bool:
        op, uid = doc.get("op"), doc.get("uid")
        if not isinstance(uid, str):
            self.metrics.reports_recoveries.inc({"reason": jn.REASON_DECODE})
            return False
        if op == "del":
            self._rows.pop(uid, None)
            return True
        if op != "put":
            self.metrics.reports_recoveries.inc({"reason": jn.REASON_DECODE})
            return False
        try:
            rows = [[str(c) for c in r] for r in doc.get("rows", [])]
            self._rows[uid] = (str(doc["sha"]), str(doc["ps"]),
                               str(doc.get("ns", "")),
                               str(doc.get("kind", "")),
                               str(doc.get("name", "")), rows)
            return True
        except (KeyError, TypeError, ValueError):
            self.metrics.reports_recoveries.inc({"reason": jn.REASON_DECODE})
            return False

    def sync(self) -> None:
        """Compact when the journal is over threshold — called once per
        scan tick, mirroring the columnar store's per-tick sync."""
        with self._lock:
            self._maybe_compact_locked()

    def close(self, compact: bool = True) -> None:
        """Clean shutdown: compact unconditionally (an empty journal at
        next boot means no replay recovery to count) and close the WAL.
        ``compact=False`` is the read-only close (`kyverno-tpu report`):
        the directory is left exactly as recovered."""
        with self._lock:
            if self._journal_fh is not None:
                if compact:
                    self._compact_locked()
                try:
                    self._journal_fh.close()
                except OSError:
                    pass
                self._journal_fh = None

    # -- readers

    def aggregate(self) -> Dict[str, PolicyReport]:
        """Reconstruct wgpolicyk8s.io/v1alpha2-shaped reports from base
        rows — the same shape as ReportAggregator.aggregate(), so
        ``/reports`` can serve either source interchangeably."""
        with self._lock:
            recs = sorted(self._rows.items())
        reports: Dict[str, PolicyReport] = {}
        for uid, (sha, _ps, ns, kind, name, rows) in recs:
            for policy, rule, result in rows:
                reports.setdefault(ns, PolicyReport(ns)).results.append(
                    ReportResult(policy=policy, rule=rule, result=result,
                                 resource_uid=uid, resource_kind=kind,
                                 resource_name=name, resource_namespace=ns))
        return reports

    def summary(self) -> Dict[str, int]:
        with self._lock:
            out = {k: 0 for k in RESULT_NAMES}
            for result, n in self._totals.items():
                if result in out:
                    out[result] = n
            return out

    def namespaces(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {ns: dict(counts)
                    for ns, counts in sorted(self._ns_counts.items())}

    def policies(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {policy: dict(counts)
                    for policy, counts in sorted(self._policy_counts.items())}

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": True,
                "directory": self.directory,
                "persistent": self._journal_fh is not None,
                "resources": len(self._rows),
                "namespaces": len(self._ns_counts),
                "seq": self._seq,
                "journal_bytes": self._journal_bytes,
                "journal_max_bytes": self.journal_max_bytes,
                "totals": dict(self._totals),
                **self.stats,
            }


def _bump(table: Dict[str, Dict[str, int]], key: str, result: str,
          delta: int) -> None:
    """Count-table bump that prunes zeros: fold/unfold sequences leave
    the table bit-identical to one built fresh (no zero-count ghosts)."""
    cell = table.setdefault(key, {})
    v = cell.get(result, 0) + delta
    if v:
        cell[result] = v
    else:
        cell.pop(result, None)
    if not cell:
        table.pop(key, None)


# -- process-global store (mirrors cluster/columnar.py's singleton)

_store: Optional[ReportStore] = None
_store_lock = threading.Lock()


def configure_reports(directory: Optional[str] = None, enabled: bool = True,
                      journal_max_bytes: Optional[int] = None
                      ) -> Optional[ReportStore]:
    """(Re)build the process-global report store. ``directory=None``
    falls back to ``KYVERNO_TPU_REPORTS_DIR`` (else in-memory);
    ``journal_max_bytes`` falls back to
    ``KYVERNO_TPU_REPORTS_JOURNAL_MAX`` (else 4 MiB)."""
    global _store
    directory = directory or os.environ.get("KYVERNO_TPU_REPORTS_DIR") or None
    if journal_max_bytes is None:
        try:
            journal_max_bytes = int(
                os.environ.get("KYVERNO_TPU_REPORTS_JOURNAL_MAX", ""))
        except ValueError:
            journal_max_bytes = None
    with _store_lock:
        if _store is not None:
            try:
                _store.close()
            except Exception:
                pass
        if not enabled:
            _store = None
            return None
        if directory:
            try:
                os.makedirs(directory, exist_ok=True)
            except OSError:
                pass  # ReportStore.__init__ routes this through the ladder
        kw: Dict[str, Any] = {}
        if journal_max_bytes is not None:
            kw["journal_max_bytes"] = journal_max_bytes
        _store = ReportStore(directory=directory, **kw)
        return _store


def get_report_store() -> Optional[ReportStore]:
    with _store_lock:
        return _store


def reset_reports() -> None:
    global _store
    with _store_lock:
        if _store is not None:
            try:
                _store.close()
            except Exception:
                pass
        _store = None


def reports_state() -> Dict[str, Any]:
    with _store_lock:
        if _store is None:
            return {"enabled": False}
        store = _store
    return store.state()
