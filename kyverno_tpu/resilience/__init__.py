"""Resilience layer — deterministic degradation for every failure mode.

Three building blocks, wired through the device, context, and serving
layers:

- ``breaker``: circuit breaker around the TPU device plane; tripped
  batches route to the scalar oracle (bit-identical verdicts).
- ``retry``: jittered exponential backoff under deadline budgets for
  the pluggable context backends and the GlobalContext refresh loop.
- ``faults``: named-site fault injection (``KYVERNO_TPU_FAULTS``) so
  chaos behavior is reproducible in CI.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker, tpu_breaker
from .faults import (FaultConfigError, FaultInjected, FaultRegistry,
                     FaultSpec, global_faults)
from .retry import (DEFAULT_RETRY, Deadline, PermanentError,
                    RetryBudgetExceeded, RetryPolicy, retry_call)

__all__ = [
    "CLOSED",
    "CircuitBreaker",
    "DEFAULT_RETRY",
    "Deadline",
    "FaultConfigError",
    "FaultInjected",
    "FaultRegistry",
    "FaultSpec",
    "HALF_OPEN",
    "OPEN",
    "PermanentError",
    "RetryBudgetExceeded",
    "RetryPolicy",
    "global_faults",
    "retry_call",
    "tpu_breaker",
]
