"""Resilience layer — deterministic degradation for every failure mode.

Three building blocks, wired through the device, context, and serving
layers:

- ``breaker``: circuit breaker around the TPU device plane; tripped
  batches route to the scalar oracle (bit-identical verdicts).
- ``retry``: jittered exponential backoff under deadline budgets for
  the pluggable context backends and the GlobalContext refresh loop.
- ``faults``: named-site fault injection (``KYVERNO_TPU_FAULTS``) so
  chaos behavior is reproducible in CI.
- ``storage``: the shim every durability surface writes through, plus
  the per-surface OK/DEGRADED ladder that turns ENOSPC/EIO/EROFS into
  a counted memory-mode instead of a crash.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker, tpu_breaker
from .faults import (FaultConfigError, FaultInjected, FaultRegistry,
                     FaultSpec, ShortWrite, global_faults)
from .retry import (DEFAULT_RETRY, Deadline, PermanentError,
                    RetryBudgetExceeded, RetryPolicy, retry_call)
from .storage import (StorageHealth, StorageHealthRegistry, global_storage,
                      reset_storage, storage_health, storage_state)

__all__ = [
    "CLOSED",
    "CircuitBreaker",
    "DEFAULT_RETRY",
    "Deadline",
    "FaultConfigError",
    "FaultInjected",
    "FaultRegistry",
    "FaultSpec",
    "HALF_OPEN",
    "OPEN",
    "PermanentError",
    "RetryBudgetExceeded",
    "RetryPolicy",
    "ShortWrite",
    "StorageHealth",
    "StorageHealthRegistry",
    "global_faults",
    "global_storage",
    "reset_storage",
    "retry_call",
    "storage_health",
    "storage_state",
    "tpu_breaker",
]
