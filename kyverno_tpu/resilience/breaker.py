"""Circuit breaker — degradation as a first-class state.

The TPU device plane is one failure unit: a wedged runtime, a
recompile loop, or a driver fault takes out every batch, not one
request. The breaker makes that degradation deterministic (PAPERS.md,
"Applying static code analysis to firewall policies": policy engines
must fail *predictably*): after ``failure_threshold`` consecutive
device errors the breaker OPENs and callers route whole batches to the
scalar oracle — verdicts stay bit-identical, only latency degrades.
After ``reset_timeout_s`` one half-open probe batch is let through;
success closes the breaker, failure re-opens it.

State and transitions are exported on /metrics
(kyverno_tpu_breaker_state, kyverno_tpu_breaker_transitions_total) so
a trip is an alert, not a silent slowdown.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    """Thread-safe consecutive-failure breaker with half-open probes.

    Protocol: callers ask ``allow()`` before attempting the protected
    operation, then report ``record_success()`` / ``record_failure()``.
    ``allow() is False`` means "go straight to the fallback path".
    """

    def __init__(
        self,
        name: str = "tpu",
        failure_threshold: int = 3,
        reset_timeout_s: float = 10.0,
        half_open_probes: int = 1,
        clock=time.monotonic,
        metrics=None,
    ) -> None:
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_probes = half_open_probes
        # constructor tuning is the canonical tuning: reset() restores
        # it unless the caller retunes explicitly, so a test that tunes
        # the process-wide breaker can't leak its knobs forward
        self._default_failure_threshold = failure_threshold
        self._default_reset_timeout_s = reset_timeout_s
        self._clock = clock
        if metrics is None:
            from ..observability.metrics import global_registry

            metrics = global_registry
        self.metrics = metrics
        self._lock = threading.Lock()
        self._state = CLOSED                 # guarded-by: _lock
        self._consecutive_failures = 0       # guarded-by: _lock
        self._opened_at: Optional[float] = None  # guarded-by: _lock
        self._probes_in_flight = 0           # guarded-by: _lock
        self._publish_state_locked()

    # -- introspection

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def reset(self, failure_threshold: Optional[int] = None,
              reset_timeout_s: Optional[float] = None) -> None:
        """Force-close and retune (tests, operator action). Omitted
        tuning args restore the constructor defaults — a bare reset()
        is a full reset, not a state-only reset that silently keeps a
        previous caller's retuning."""
        with self._lock:
            self.failure_threshold = (
                failure_threshold if failure_threshold is not None
                else self._default_failure_threshold)
            self.reset_timeout_s = (
                reset_timeout_s if reset_timeout_s is not None
                else self._default_reset_timeout_s)
            self._consecutive_failures = 0
            self._probes_in_flight = 0
            self._opened_at = None
            if self._state != CLOSED:
                self._transition_locked(CLOSED)
            else:
                self._publish_state_locked()

    # -- protocol

    def allow(self) -> bool:
        with self._lock:
            if self._state == OPEN:
                if (self._opened_at is not None
                        and self._clock() - self._opened_at >= self.reset_timeout_s):
                    self._transition_locked(HALF_OPEN)
                    self._probes_in_flight = 0
                else:
                    return False
            if self._state == HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    return False
                self._probes_in_flight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state in (HALF_OPEN, OPEN):
                # OPEN can see a success when a probe raced the trip;
                # either way the device path just worked end to end
                self._transition_locked(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._open_locked()
            elif (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._open_locked()

    # -- internals (lock held)

    def _open_locked(self) -> None:
        self._opened_at = self._clock()
        self._transition_locked(OPEN)

    def _transition_locked(self, to: str) -> None:
        frm, self._state = self._state, to
        if frm != to:
            self.metrics.breaker_transitions.inc(
                {"breaker": self.name, "from": frm, "to": to})
            # a transition inside a traced operation (dispatch span)
            # lands on that span, so the trace of the batch that tripped
            # or healed the breaker says so itself
            from ..observability.tracing import global_tracer

            global_tracer.add_event(
                "breaker_transition", breaker=self.name,
                from_state=frm, to_state=to,
                consecutive_failures=self._consecutive_failures)
            # flight recorder: a breaker transition is an incident
            # moment — spool the last N decisions (the evidence) when a
            # spool dir is configured, and log it structurally.
            # Transitions are rare, so the file write under the breaker
            # lock is acceptable; the cooldown bounds a flapping breaker
            try:
                from ..observability.flightrecorder import global_flight

                global_flight.on_breaker_transition(self.name, frm, to)
            except Exception:
                pass
            try:
                from ..observability.log import global_oplog

                global_oplog.emit(
                    "breaker_transition",
                    level="warn" if to == OPEN else "info",
                    breaker=self.name, from_state=frm, to_state=to,
                    consecutive_failures=self._consecutive_failures)
            except Exception:
                pass
        self._publish_state_locked()

    def _publish_state_locked(self) -> None:
        self.metrics.breaker_state.set(
            _STATE_GAUGE[self._state], {"breaker": self.name})


# the process-wide breaker guarding the TPU device plane: device errors
# are device-wide, so every TpuEngine instance (they churn with policy
# revisions) shares one breaker unless a caller injects its own
_default_lock = threading.Lock()
_default_breaker: Optional[CircuitBreaker] = None


def tpu_breaker() -> CircuitBreaker:
    global _default_breaker
    with _default_lock:
        if _default_breaker is None:
            _default_breaker = CircuitBreaker(name="tpu")
        return _default_breaker
