"""Fault-injection registry — chaos behavior as a reproducible fixture.

Every failure mode this repo defends against has a *named site* where
the failure physically happens:

    tpu.dispatch        the jitted device program call (tpu/engine.py)
    context.api_call    the apiCall context backend (contextloaders.py)
    context.image_data  the imageRegistry context backend
    gctx.refresh        the GlobalContext external-API poll (entry.py)
    serving.flush       the admission pipeline's batch evaluation
    serving.hedge       the hedged scalar dispatch racing an in-flight
                        device batch (serving/batcher.py) — a raise
                        here degrades the hedge to plain waiting, a
                        delay makes the device win the race
    policyset.compile   the lifecycle manager's compile-ahead lowering
                        (full-set compiles AND per-policy bisect probes)
    encode.pool_dispatch  the encoder pool's supervisor-side chunk
                          dispatch (encode/pool.py)
    encode.worker       the encode executed INSIDE a pool worker
                        process (encode/worker.py)
    fleet.heartbeat     a replica's outbound membership heartbeat
                        (fleet/manager.py) — a raise here looks like a
                        network partition: the peer's lease keeps
                        aging and failover engages at the TTL
    fleet.peer_fetch    the verdict-cache fetch-on-miss call to a peer
                        (fleet/peering.py) — degrades to local compute
    fleet.gossip        the async push of freshly computed verdict
                        columns to peers (fleet/manager.py)
    mutate.triage       the needs-mutation device batch over the
                        compiled mutate bank (tpu/engine.py) — a raise
                        degrades every row to HOST, routing the whole
                        batch to the scalar patcher bit-identically
    mutate.patch        a policy's template-stamp pass in the mutation
                        coordinator (mutation/coordinator.py) — a raise
                        falls that policy back to the scalar patcher
    reports.fold        the incremental report delta fold
                        (reports/store.py) — a raise mid-fold degrades
                        to a full derived-count rebuild from base rows,
                        counted, never a wrong report
    reports.journal     the report WAL append (reports/store.py) — a
                        raise loses the delta from the journal (counted;
                        the in-memory fold still lands); corrupt writes
                        a mangled wire record the replay ladder must
                        truncate at
    storage.open        opening/creating a durability file or dir
                        (resilience/storage.py shim) — every surface's
                        open_append/makedirs routes through it
    storage.write       a durability write (journal frame, spool line,
                        oplog record, span export, arena flush)
    storage.fsync       the fsync of a durability file
    storage.replace     the atomic os.replace() publishing a snapshot,
                        manifest, or rotated spool file

The four ``storage.*`` sites additionally accept the OS-error modes
``enospc`` / ``eio`` / ``erofs`` — ``fire()`` raises a real ``OSError``
with the matching errno instead of ``FaultInjected``, so the injected
failure and a genuine disk failure travel the SAME except-clause — and
``storage.write`` accepts ``short`` (write a partial prefix, then
raise EIO: the torn-write fixture). Scope a storage fault to one
surface with ``match=<surface>`` (the shim's payload is
``"<surface>:<path>"``).

Tests (and the ``KYVERNO_TPU_FAULTS`` env knob) arm a site with a
probability- or count-based trigger and a mode — ``raise``, ``delay``,
``corrupt`` (shape-mangle the site's result), or ``crash``
(``os._exit`` the current process — only meaningful at
``encode.worker``, where a supervised worker process dying is a
first-class failure the pool must absorb) — so degradation paths
are exercised deterministically in CI instead of waiting for real
hardware to misbehave. Probability triggers draw from a per-fault
seeded RNG, making a chaos run replayable. A ``match=<substring>``
option scopes a fault to calls whose payload (e.g. the chunk of
resources a worker is encoding) contains the substring — the poison-
resource chaos tests use it to make ONE resource reliably lethal.

``corrupt`` is only meaningful at sites that pass their RESULT through
``FaultRegistry.corrupt()`` (today: ``tpu.dispatch``, whose verdict
table is shape-validated downstream, and ``reports.journal``, whose
mangled wire record the WAL replay ladder must truncate at). Arming
corrupt at a raise/delay only site is rejected at arm time — a chaos
run that silently injects nothing is worse than no chaos run.

Env syntax (';'-separated site specs)::

    KYVERNO_TPU_FAULTS="tpu.dispatch:raise:p=0.3;gctx.refresh:raise:count=3"
    site ':' mode [':' key=value (',' key=value)*]
    keys: p=<float 0..1> | count=<int first-N calls> | delay_s=<float>
          | seed=<int> | match=<substring of the call payload>
          | flip=1 (corrupt mode only: flip verdict VALUES with the
            shape intact — the silent wrong answer that passes every
            shape check and is only caught by shadow verification)
"""

from __future__ import annotations

import errno as _errno
import os
import threading
import time
from dataclasses import dataclass, field
from random import Random
from typing import Any, Dict, Optional

SITE_TPU_DISPATCH = "tpu.dispatch"
SITE_CONTEXT_API_CALL = "context.api_call"
SITE_CONTEXT_IMAGE_DATA = "context.image_data"
SITE_GCTX_REFRESH = "gctx.refresh"
SITE_SERVING_FLUSH = "serving.flush"
SITE_SERVING_HEDGE = "serving.hedge"
SITE_POLICYSET_COMPILE = "policyset.compile"
SITE_ENCODE_POOL_DISPATCH = "encode.pool_dispatch"
SITE_ENCODE_WORKER = "encode.worker"
SITE_FLEET_HEARTBEAT = "fleet.heartbeat"
SITE_FLEET_PEER_FETCH = "fleet.peer_fetch"
SITE_FLEET_GOSSIP = "fleet.gossip"
SITE_FLEET_TELEMETRY = "fleet.telemetry"
SITE_MUTATE_TRIAGE = "mutate.triage"
SITE_MUTATE_PATCH = "mutate.patch"
SITE_REPORTS_FOLD = "reports.fold"
SITE_REPORTS_JOURNAL = "reports.journal"
SITE_STORAGE_OPEN = "storage.open"
SITE_STORAGE_WRITE = "storage.write"
SITE_STORAGE_FSYNC = "storage.fsync"
SITE_STORAGE_REPLACE = "storage.replace"

KNOWN_SITES = frozenset({
    SITE_TPU_DISPATCH, SITE_CONTEXT_API_CALL, SITE_CONTEXT_IMAGE_DATA,
    SITE_GCTX_REFRESH, SITE_SERVING_FLUSH, SITE_SERVING_HEDGE,
    SITE_POLICYSET_COMPILE, SITE_ENCODE_POOL_DISPATCH, SITE_ENCODE_WORKER,
    SITE_FLEET_HEARTBEAT, SITE_FLEET_PEER_FETCH, SITE_FLEET_GOSSIP,
    SITE_FLEET_TELEMETRY,
    SITE_MUTATE_TRIAGE, SITE_MUTATE_PATCH,
    SITE_REPORTS_FOLD, SITE_REPORTS_JOURNAL,
    SITE_STORAGE_OPEN, SITE_STORAGE_WRITE, SITE_STORAGE_FSYNC,
    SITE_STORAGE_REPLACE,
})

MODES = ("raise", "delay", "corrupt", "crash",
         "enospc", "eio", "erofs", "short")

# OS-error modes: fire() raises OSError with the matching errno — the
# SAME exception class and errno a real full/erroring/read-only disk
# produces, so the degraded-storage ladder cannot tell (and must not
# care) whether the failure was injected or genuine.
OS_ERROR_MODES = {
    "enospc": _errno.ENOSPC,
    "eio": _errno.EIO,
    "erofs": _errno.EROFS,
}

STORAGE_SITES = frozenset({SITE_STORAGE_OPEN, SITE_STORAGE_WRITE,
                           SITE_STORAGE_FSYNC, SITE_STORAGE_REPLACE})

# sites whose result flows through FaultRegistry.corrupt(); every other
# site only has the fire() (raise/delay) hook. fleet.telemetry filters
# the OUTGOING snapshot doc server-side — the chaos fixture for the
# receiver's checksum/trust-ladder rejection path
CORRUPTIBLE_SITES = frozenset({SITE_TPU_DISPATCH, SITE_REPORTS_JOURNAL,
                               SITE_FLEET_TELEMETRY})

# sites where mode=crash (os._exit) is meaningful: the site runs in a
# SUPERVISED child process whose death the parent is built to absorb.
# Crashing an unsupervised site would just kill the engine — reject it
# at arm time like corrupt-at-non-filtering sites.
CRASHABLE_SITES = frozenset({SITE_ENCODE_WORKER})


class FaultInjected(RuntimeError):
    """The error an armed ``raise`` fault throws at its site."""


class ShortWrite(OSError):
    """Raised by an armed ``short`` fault at ``storage.write``. The
    write shim catches it, writes a partial prefix of the buffer for
    real, then re-raises it as the EIO a torn write surfaces as — the
    fixture for every loadable-prefix recovery property."""

    def __init__(self) -> None:
        super().__init__(_errno.EIO, "injected short write")


class FaultConfigError(ValueError):
    """Malformed KYVERNO_TPU_FAULTS spec / arm() arguments."""


@dataclass
class FaultSpec:
    site: str
    mode: str = "raise"
    p: Optional[float] = None       # probability trigger per call
    count: Optional[int] = None     # trigger on the first N calls
    delay_s: float = 0.01           # sleep for mode=delay
    seed: int = 0                   # RNG seed for probability triggers
    match: Optional[str] = None     # only fire when payload contains this
    # corrupt variant: instead of shape-mangling the result (which the
    # engine's shape validation CATCHES, exercising the breaker
    # ladder), flip verdict VALUES in place — a shape-valid wrong
    # answer, the silent-device-lie failure class only continuous
    # shadow verification (observability/verification.py) can detect
    flip: bool = False
    calls: int = 0                  # observed calls (all)
    fired: int = 0                  # calls that triggered
    _rng: Random = field(default_factory=Random, repr=False)

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise FaultConfigError(f"unknown fault mode {self.mode!r}")
        if self.flip and self.mode != "corrupt":
            raise FaultConfigError(
                "flip=1 only modifies corrupt-mode faults")
        if self.p is None and self.count is None:
            self.p = 1.0  # armed with no trigger = always fires
        if self.p is not None and not (0.0 <= self.p <= 1.0):
            raise FaultConfigError(f"fault probability out of range: {self.p}")
        self._rng = Random(self.seed)

    def _triggers(self) -> bool:
        self.calls += 1
        if self.count is not None:
            if self.fired >= self.count:
                return False
        elif self.p is not None and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True


def _corrupt(value: Any) -> Any:
    """Shape-mangle a site result: the wrong-shaped answer a sick
    device or a half-written upstream response produces."""
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return value[..., :-1] if value.size else value
    except ImportError:  # numpy always present in this repo; belt+braces
        pass
    if isinstance(value, list):
        return value[:-1]
    if isinstance(value, dict):
        out = dict(value)
        if out:
            out.pop(next(iter(out)))
        return out
    if isinstance(value, str):
        return value[:-1]
    return None


def _flip(value: Any) -> Any:
    """Value-corrupt a verdict table WITHOUT changing its shape: swap
    PASS(0) <-> FAIL(2) cells. This clears every downstream shape/dtype
    check — exactly a device silently computing the wrong answer —
    so it is the fixture for shadow-verification divergence tests."""
    try:
        import numpy as np

        if isinstance(value, np.ndarray) and \
                np.issubdtype(value.dtype, np.integer):
            out = value.copy()
            out[value == 0] = 2
            out[value == 2] = 0
            return out
    except ImportError:
        pass
    return value


class FaultRegistry:
    """Armed faults by site. ``fire()`` is the raise/delay hook placed
    BEFORE the protected operation; ``corrupt()`` filters the
    operation's RESULT. Unarmed sites cost one dict lookup."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: Dict[str, FaultSpec] = {}

    # -- arming

    def arm(self, site: str, mode: str = "raise", p: Optional[float] = None,
            count: Optional[int] = None, delay_s: float = 0.01,
            seed: int = 0, match: Optional[str] = None,
            flip: bool = False) -> FaultSpec:
        if site not in KNOWN_SITES:
            raise FaultConfigError(
                f"unknown fault site {site!r} (known: {sorted(KNOWN_SITES)})")
        if mode == "corrupt" and site not in CORRUPTIBLE_SITES:
            raise FaultConfigError(
                f"site {site!r} does not filter results through corrupt() "
                f"(corruptible: {sorted(CORRUPTIBLE_SITES)}) — arming it "
                f"would inject nothing")
        if mode == "crash" and site not in CRASHABLE_SITES:
            raise FaultConfigError(
                f"site {site!r} does not run in a supervised child process "
                f"(crashable: {sorted(CRASHABLE_SITES)}) — crashing it "
                f"would kill the engine, not exercise recovery")
        if mode in OS_ERROR_MODES and site not in STORAGE_SITES:
            raise FaultConfigError(
                f"mode {mode!r} is an OS-error mode; only the storage shim "
                f"sites ({sorted(STORAGE_SITES)}) route OSError through the "
                f"degraded-storage ladder")
        if mode == "short" and site != SITE_STORAGE_WRITE:
            raise FaultConfigError(
                f"mode 'short' (partial write then EIO) is only meaningful "
                f"at {SITE_STORAGE_WRITE!r}")
        spec = FaultSpec(site=site, mode=mode, p=p, count=count,
                         delay_s=delay_s, seed=seed, match=match, flip=flip)
        with self._lock:
            self._armed[site] = spec
        return spec

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._armed.clear()
            else:
                self._armed.pop(site, None)

    def armed(self) -> Dict[str, FaultSpec]:
        with self._lock:
            return dict(self._armed)

    def arm_from_string(self, text: str) -> int:
        """Parse the KYVERNO_TPU_FAULTS syntax; returns #faults armed."""
        n = 0
        for chunk in (text or "").split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            parts = chunk.split(":")
            if len(parts) < 2:
                raise FaultConfigError(
                    f"fault spec {chunk!r} needs at least site:mode")
            site, mode = parts[0].strip(), parts[1].strip()
            kw: Dict[str, Any] = {}
            for pair in ",".join(parts[2:]).split(","):
                pair = pair.strip()
                if not pair:
                    continue
                if "=" not in pair:
                    raise FaultConfigError(f"bad fault option {pair!r}")
                k, v = (s.strip() for s in pair.split("=", 1))
                if k == "p":
                    kw["p"] = float(v)
                elif k == "count":
                    kw["count"] = int(v)
                elif k == "delay_s":
                    kw["delay_s"] = float(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                elif k == "match":
                    kw["match"] = v
                elif k == "flip":
                    kw["flip"] = v.lower() not in ("0", "false", "off", "")
                else:
                    raise FaultConfigError(f"unknown fault option {k!r}")
            self.arm(site, mode=mode, **kw)
            n += 1
        return n

    # -- firing

    def fire(self, site: str, payload: Any = None) -> None:
        """Raise/delay/crash hook. A ``corrupt`` fault never fires here
        — its trigger is consumed by ``corrupt()`` on the result
        instead. ``payload`` scopes ``match=`` faults: a string (or a
        zero-arg callable returning one, evaluated only when a match
        fault is armed — building the text is not free) describing the
        call's content."""
        spec = self._armed.get(site)  # GIL-safe fast path when unarmed
        if spec is None or spec.mode == "corrupt":
            return
        if spec.match is not None:
            text = payload() if callable(payload) else (payload or "")
            if spec.match not in text:
                return
        with self._lock:
            triggered = spec._triggers()
        if not triggered:
            return
        self._count(spec)
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
            return
        if spec.mode == "crash":
            # the supervised-worker death path: no cleanup, no excuses —
            # exactly what an OOM kill or a segfaulting extension does
            os._exit(70)
        if spec.mode in OS_ERROR_MODES:
            code = OS_ERROR_MODES[spec.mode]
            raise OSError(code, os.strerror(code), str(payload() if
                          callable(payload) else payload or site))
        if spec.mode == "short":
            raise ShortWrite()
        raise FaultInjected(f"injected fault at {site}")

    def corrupt(self, site: str, value: Any) -> Any:
        """Result filter for ``corrupt``-mode faults."""
        spec = self._armed.get(site)
        if spec is None or spec.mode != "corrupt":
            return value
        with self._lock:
            triggered = spec._triggers()
        if not triggered:
            return value
        self._count(spec)
        return _flip(value) if spec.flip else _corrupt(value)

    @staticmethod
    def _count(spec: FaultSpec) -> None:
        from ..observability.metrics import global_registry
        from ..observability.tracing import global_tracer

        global_registry.faults_injected.inc(
            {"site": spec.site, "mode": spec.mode})
        # chaos runs must be attributable per-trace: the span under
        # which the fault fired records it as an event
        global_tracer.add_event("fault_injected", site=spec.site,
                                mode=spec.mode, fired=spec.fired)


global_faults = FaultRegistry()
# env arming happens once at import: the knob is a process-launch
# switch (chaos CI runs), not a hot-reloaded config. A malformed spec
# fails the process LOUDLY here — silently running a chaos suite with
# no chaos armed would be the worst possible degradation — but names
# the env var so the operator knows exactly what to fix.
try:
    global_faults.arm_from_string(os.environ.get("KYVERNO_TPU_FAULTS", ""))
except FaultConfigError as e:
    raise FaultConfigError(f"malformed KYVERNO_TPU_FAULTS env value: {e}") \
        from None
