"""Retry with jittered exponential backoff under a deadline budget.

The reference engine retries external context calls (apiCall's client
retry semantics) and bounds each entry's blast radius with the webhook
budget. ``retry_call`` packages both: attempts back off exponentially
with symmetric jitter, and the whole loop is clamped to a ``Deadline``
— a retry that could not finish inside the remaining budget is not
attempted, so a flaky backend degrades into ONE bounded stall, never
an unbounded hot-loop.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class Deadline:
    """Absolute time budget that propagates through call layers."""

    def __init__(self, budget_s: Optional[float], clock=time.monotonic) -> None:
        self._clock = clock
        self.at: Optional[float] = None if budget_s is None \
            else clock() + budget_s

    def remaining(self) -> float:
        if self.at is None:
            return float("inf")
        return self.at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


class RetryBudgetExceeded(TimeoutError):
    """The deadline budget ran out before an attempt succeeded."""


class PermanentError(Exception):
    """Marker for failures retrying cannot fix — a 404-style lookup, a
    validation rejection, a misconfigured reference. ``retry_call``
    re-raises these immediately instead of burning attempts and backoff
    against a backend that will give the same answer every time.
    Pluggable backends (``DataSources.api_call`` / ``image_data``,
    GlobalContext executors) raise it (or a subclass) to opt a failure
    out of retries; anything else is treated as transient."""


@dataclass(frozen=True)
class RetryPolicy:
    """APICall-style retry knobs (retries + exponential backoff)."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5          # +/- fraction of the computed delay
    deadline_s: Optional[float] = 5.0  # per-call total budget

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before attempt ``attempt+1`` (0-based failures)."""
        d = min(self.max_delay_s, self.base_delay_s * self.multiplier ** attempt)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)


DEFAULT_RETRY = RetryPolicy()


def retry_call(
    fn: Callable[[], T],
    policy: RetryPolicy = DEFAULT_RETRY,
    deadline: Optional[Deadline] = None,
    site: str = "",
    clock=time.monotonic,
    sleep=time.sleep,
    rng: Optional[random.Random] = None,
    metrics=None,
) -> T:
    """Call ``fn`` until it succeeds, attempts run out, or the deadline
    budget cannot cover the next backoff. Raises the last error (or
    RetryBudgetExceeded when the budget expired before any attempt)."""
    if metrics is None:
        from ..observability.metrics import global_registry

        metrics = global_registry
    from ..observability.tracing import global_tracer

    rng = rng or random.Random()
    if deadline is None:
        deadline = Deadline(policy.deadline_s, clock=clock)
    last: Optional[BaseException] = None
    for attempt in range(max(policy.max_attempts, 1)):
        if deadline.expired():
            break
        try:
            out = fn()
            if attempt:
                metrics.retry_attempts.inc(
                    {"site": site or "unknown", "outcome": "recovered"},
                    value=attempt)
                global_tracer.add_event(
                    "retry_recovered", site=site or "unknown",
                    attempts=attempt + 1)
            return out
        except PermanentError:
            # deterministic failure: surface it now, the backend will
            # not answer differently on attempt 2
            metrics.retry_attempts.inc(
                {"site": site or "unknown", "outcome": "permanent"})
            raise
        except Exception as e:  # noqa: BLE001 — other failures are transient
            last = e
            global_tracer.add_event(
                "retry_attempt_failed", site=site or "unknown",
                attempt=attempt + 1, error=f"{type(e).__name__}: {e}")
            if attempt + 1 >= policy.max_attempts:
                break
            pause = policy.delay(attempt, rng)
            # a backoff the budget cannot cover is a budget failure NOW,
            # not a sleep that wakes up past the caller's deadline
            if pause >= deadline.remaining():
                break
            sleep(pause)
    metrics.retry_attempts.inc({"site": site or "unknown", "outcome": "exhausted"})
    if last is None:
        raise RetryBudgetExceeded(
            f"{site or 'call'}: deadline budget exhausted before an attempt")
    raise last
