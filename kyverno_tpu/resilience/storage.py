"""Storage shim + per-surface degraded-storage ladder.

Every durability surface in the engine (reports journal/snapshot,
columnar arenas, flight spool, divergence log, op-log, OTLP trace
export, XLA compile cache) routes its filesystem side effects through
the thin wrappers here — ``open_append`` / ``write_frame`` /
``atomic_replace`` / ``fsync`` / ``mmap_sync`` / ``makedirs`` — for
two reasons:

1. **One fault choke point.** Each wrapper fires a ``storage.*`` fault
   site (``resilience/faults.py``) before touching the OS, so chaos
   runs can inject ENOSPC / EIO / EROFS / short writes per surface
   (``match=<surface>``) and the injected ``OSError`` travels the SAME
   except-clause a genuinely full or erroring disk does. Injected and
   real failures are indistinguishable by construction.

2. **One degradation ladder.** Every wrapper reports into a per-surface
   :class:`StorageHealth`: OK -> DEGRADED on the first ``OSError``,
   then capped jittered re-probes (``RetryPolicy.delay``) until a probe
   write succeeds and the surface heals. While degraded, each surface
   runs a defined *memory mode* chosen by its owner — reports fold
   in memory only (bit-identical) and compact on heal, the columnar
   store drops its mmap backing to anonymous arenas, spool/op-log/trace
   surfaces drop-and-count, the XLA cache disables itself — so a sick
   disk degrades durability and NOTHING else: verdicts stay correct,
   serving stays up, readiness stays green (a ``/readyz`` advisory and
   the ``kyverno_storage_degraded`` gauge carry the alert instead).

Transitions (and only transitions) emit an op-log event, a tracer
event, and flip the gauge; every error counts on
``kyverno_storage_errors_total{surface,kind}`` and every heal on
``kyverno_storage_heals_total{surface}``. All emission happens OUTSIDE
the health lock — the op-log is itself a guarded surface, and a ladder
that deadlocks reporting its own degradation would be worse than the
disk failure it survived.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from random import Random
from typing import IO, Any, Dict, Optional

from .faults import (SITE_STORAGE_FSYNC, SITE_STORAGE_OPEN,
                     SITE_STORAGE_REPLACE, SITE_STORAGE_WRITE, ShortWrite,
                     global_faults)
from .retry import RetryPolicy

# The durability surfaces. One StorageHealth per surface; the shim's
# fault payload is "<surface>:<path>" so match=<surface> scopes a
# chaos run to exactly one of them.
SURFACE_REPORTS = "reports"
SURFACE_COLUMNAR = "columnar"
SURFACE_FLIGHT = "flight_spool"
SURFACE_DIVERGENCES = "divergences"
SURFACE_OPLOG = "oplog"
SURFACE_TRACE = "trace_export"
SURFACE_XLA_CACHE = "xla_cache"

SURFACES = (SURFACE_REPORTS, SURFACE_COLUMNAR, SURFACE_FLIGHT,
            SURFACE_DIVERGENCES, SURFACE_OPLOG, SURFACE_TRACE,
            SURFACE_XLA_CACHE)

OK = "ok"
DEGRADED = "degraded"

# Re-probe cadence while degraded: ~0.5s after the first failure,
# doubling (jittered) to a 15s cap — frequent enough that freed disk
# space restores durability within seconds, slow enough that a dead
# disk costs one failed syscall per surface per 15s, not a hot loop.
REPROBE_POLICY = RetryPolicy(max_attempts=1, base_delay_s=0.5,
                             max_delay_s=15.0, multiplier=2.0,
                             jitter=0.5, deadline_s=None)
_MAX_BACKOFF_STEP = 8


def classify_os_error(err: OSError) -> str:
    """Map an OSError to the error-kind label. EFBIG (RLIMIT_FSIZE —
    how CI manufactures a *real* full disk) and EDQUOT are
    space-exhaustion like ENOSPC; EACCES/EPERM/EROFS are all
    'the mount went read-only on us' class."""
    no = getattr(err, "errno", None)
    if no in (errno.ENOSPC, errno.EFBIG, getattr(errno, "EDQUOT", -1)):
        return "enospc"
    if no == errno.EIO:
        return "eio"
    if no in (errno.EROFS, errno.EACCES, errno.EPERM):
        return "erofs"
    return "other"


class StorageHealth:
    """OK/DEGRADED ladder for one durability surface.

    The contract mirrors the circuit breaker: state mutation happens
    under ``_lock``; metric/op-log/tracer emission happens after the
    lock is released and only on TRANSITIONS, so a flapping disk
    produces a bounded event stream and the op-log surface can be
    guarded by its own StorageHealth without re-entrancy."""

    def __init__(self, surface: str, policy: RetryPolicy = REPROBE_POLICY,
                 clock=time.monotonic) -> None:
        self.surface = surface
        self.policy = policy
        self._clock = clock
        self._lock = threading.Lock()
        self._rng = Random(hash(surface) & 0xFFFF)
        self._state = OK
        self._kind: Optional[str] = None
        self._errno: Optional[int] = None
        self._last_error: str = ""
        self._errors = 0
        self._drops = 0
        self._heals = 0
        self._probes = 0
        self._fail_streak = 0
        self._next_probe_at = 0.0
        self._degraded_since: Optional[float] = None

    # -- fast-path queries ------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._state == DEGRADED  # racy read is fine: advisory

    def allow(self) -> bool:
        """Gate a durability write. Healthy -> always True. Degraded ->
        True only when a re-probe is due (and then the probe slot is
        consumed, so concurrent writers don't stampede the sick disk);
        otherwise the write is a counted drop and the caller runs its
        memory mode."""
        if self._state == OK:
            return True
        with self._lock:
            if self._state == OK:
                return True
            now = self._clock()
            if now >= self._next_probe_at:
                self._probes += 1
                self._next_probe_at = now + self.policy.delay(
                    min(self._fail_streak, _MAX_BACKOFF_STEP), self._rng)
                return True
            self._drops += 1
            return False

    def count_drop(self) -> None:
        with self._lock:
            self._drops += 1

    def force_probe(self) -> None:
        """Test/ops hook: make the next ``allow()`` a probe now instead
        of waiting out the backoff."""
        with self._lock:
            self._next_probe_at = 0.0

    # -- transitions ------------------------------------------------------

    def record_error(self, err: OSError, op: str = "") -> str:
        """An OSError reached this surface (injected or real — same
        path). Degrades on first error, pushes the next probe out on
        every error. Returns the classified kind."""
        kind = classify_os_error(err)
        with self._lock:
            self._errors += 1
            self._kind = kind
            self._errno = getattr(err, "errno", None)
            self._last_error = f"{op + ': ' if op else ''}{err}"[:200]
            degrading = self._state == OK
            if degrading:
                self._state = DEGRADED
                self._degraded_since = self._clock()
            self._fail_streak += 1
            self._next_probe_at = self._clock() + self.policy.delay(
                min(self._fail_streak, _MAX_BACKOFF_STEP), self._rng)
        self._emit_error(kind)
        if degrading:
            self._emit_transition("storage_degraded", kind=kind, op=op,
                                  error=str(err))
        return kind

    def record_success(self) -> bool:
        """A guarded write landed. Heals a degraded surface (returns
        True exactly on the degraded->ok transition so the owner can
        run its re-establish-durability step, e.g. snapshot
        compaction)."""
        if self._state == OK:
            return False
        with self._lock:
            if self._state == OK:
                return False
            self._state = OK
            self._fail_streak = 0
            self._heals += 1
            self._degraded_since = None
        self._emit_transition("storage_healed", kind=self._kind or "other")
        return True

    # -- emission (never under the lock) ----------------------------------

    def _emit_error(self, kind: str) -> None:
        try:
            from ..observability.metrics import global_registry

            global_registry.storage_errors.inc(
                {"surface": self.surface, "kind": kind})
        except Exception:
            pass

    def _emit_transition(self, event: str, **fields: Any) -> None:
        healed = event == "storage_healed"
        try:
            from ..observability.metrics import global_registry

            global_registry.storage_degraded.set(
                0.0 if healed else 1.0, {"surface": self.surface})
            if healed:
                global_registry.storage_heals.inc({"surface": self.surface})
        except Exception:
            pass
        try:
            from ..observability.tracing import global_tracer

            global_tracer.add_event(event, surface=self.surface, **fields)
        except Exception:
            pass
        # The op-log is itself a guarded surface: if IT is the degraded
        # one, this emit drops-and-counts on the file sink (stderr still
        # prints) instead of recursing — OpLog checks allow() first.
        try:
            from ..observability.log import global_oplog

            global_oplog.emit(event, surface=self.surface, **fields)
        except Exception:
            pass

    def state(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "state": self._state,
                "errors": self._errors,
                "drops": self._drops,
                "heals": self._heals,
                "probes": self._probes,
            }
            if self._kind is not None:
                out["last_kind"] = self._kind
                out["last_errno"] = self._errno
                out["last_error"] = self._last_error
            if self._degraded_since is not None:
                out["degraded_for_s"] = round(
                    self._clock() - self._degraded_since, 3)
        return out


class StorageHealthRegistry:
    """Process-global surface -> StorageHealth map, created on demand
    (introspection of an unused surface must not invent state)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_surface: Dict[str, StorageHealth] = {}

    def get(self, surface: str) -> StorageHealth:
        h = self._by_surface.get(surface)
        if h is not None:
            return h
        with self._lock:
            return self._by_surface.setdefault(surface,
                                               StorageHealth(surface))

    def state(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            items = list(self._by_surface.items())
        return {s: h.state() for s, h in sorted(items)}

    def degraded_surfaces(self) -> list:
        with self._lock:
            items = list(self._by_surface.items())
        return sorted(s for s, h in items if h.degraded)

    def reset(self) -> None:
        """Test isolation: drop all surface state and zero the gauge."""
        with self._lock:
            surfaces = list(self._by_surface)
            self._by_surface.clear()
        try:
            from ..observability.metrics import global_registry

            for s in surfaces:
                global_registry.storage_degraded.remove({"surface": s})
        except Exception:
            pass


global_storage = StorageHealthRegistry()


def storage_health(surface: str) -> StorageHealth:
    return global_storage.get(surface)


def storage_state() -> Dict[str, Dict[str, Any]]:
    return global_storage.state()


def reset_storage() -> None:
    global_storage.reset()


# ---------------------------------------------------------------------------
# The shim wrappers. Each fires its fault site (payload
# "<surface>:<path>", lazily built), performs the real OS call, and —
# unless record=False — folds the outcome into the surface's
# StorageHealth. record=False is for call sites that must defer health
# accounting until after they release their own lock (the op-log,
# whose degrade event would otherwise re-enter it).


def _payload(surface: str, path: Any):
    return lambda: f"{surface}:{path}"


def _record(surface: str, err: Optional[OSError], op: str,
            record: bool) -> None:
    if not record:
        return
    h = global_storage.get(surface)
    if err is None:
        h.record_success()
    else:
        h.record_error(err, op=op)


def open_append(path: str, surface: str, *, binary: bool = False,
                buffering: int = -1, record: bool = True) -> IO[Any]:
    """Open a durability file for append (fault site storage.open)."""
    try:
        global_faults.fire(SITE_STORAGE_OPEN, _payload(surface, path))
        fh = open(path, "ab", buffering=buffering) if binary \
            else open(path, "a", buffering=buffering, encoding="utf-8")
    except OSError as e:
        _record(surface, e, "open", record)
        raise
    _record(surface, None, "open", record)
    return fh


def open_truncate(path: str, surface: str, *, binary: bool = False,
                  buffering: int = -1, record: bool = True) -> IO[Any]:
    """Open a durability file for truncate-write — snapshot/manifest
    tmp files, fresh spool segments (fault site storage.open)."""
    try:
        global_faults.fire(SITE_STORAGE_OPEN, _payload(surface, path))
        fh = open(path, "wb", buffering=buffering) if binary \
            else open(path, "w", buffering=buffering, encoding="utf-8")
    except OSError as e:
        _record(surface, e, "open", record)
        raise
    _record(surface, None, "open", record)
    return fh


def write_frame(fh: IO[Any], data, surface: str, *, path: Any = "",
                flush: bool = False, record: bool = True) -> None:
    """Write one durability frame (fault site storage.write). An armed
    ``short`` fault makes this write a partial PREFIX of the frame for
    real before raising EIO — the torn-write fixture every
    loadable-prefix recovery property is tested against."""
    try:
        try:
            global_faults.fire(SITE_STORAGE_WRITE, _payload(surface, path))
        except ShortWrite:
            try:
                fh.write(data[: max(1, len(data) // 2)])
                fh.flush()
            except (OSError, ValueError):
                pass  # the torn write already failed harder; keep the EIO
            raise
        fh.write(data)
        if flush:
            fh.flush()
    except OSError as e:
        _record(surface, e, "write", record)
        raise
    _record(surface, None, "write", record)


def fsync(fh: IO[Any], surface: str, *, path: Any = "",
          record: bool = True) -> None:
    """Flush + fsync a durability file (fault site storage.fsync)."""
    try:
        global_faults.fire(SITE_STORAGE_FSYNC, _payload(surface, path))
        fh.flush()
        os.fsync(fh.fileno())
    except OSError as e:
        _record(surface, e, "fsync", record)
        raise
    _record(surface, None, "fsync", record)


def atomic_replace(src: str, dst: str, surface: str, *,
                   record: bool = True) -> None:
    """os.replace publishing a snapshot/manifest/rotation (fault site
    storage.replace)."""
    try:
        global_faults.fire(SITE_STORAGE_REPLACE, _payload(surface, dst))
        os.replace(src, dst)
    except OSError as e:
        _record(surface, e, "replace", record)
        raise
    _record(surface, None, "replace", record)


def mmap_sync(arr, surface: str, *, path: Any = "",
              record: bool = True) -> None:
    """Flush a numpy memmap arena to its backing file (fault site
    storage.write — it is a write, just a page-cache one)."""
    try:
        global_faults.fire(SITE_STORAGE_WRITE, _payload(surface, path))
        arr.flush()
    except OSError as e:
        _record(surface, e, "mmap_sync", record)
        raise
    _record(surface, None, "mmap_sync", record)


def makedirs(path: str, surface: str, *, record: bool = True) -> None:
    """mkdir -p for a durability dir (fault site storage.open).
    NOTE: exist_ok=True succeeds on an EXISTING dir even on a
    read-only filesystem — surfaces that need writability (XLA cache)
    must follow up with ``probe_writable``."""
    try:
        global_faults.fire(SITE_STORAGE_OPEN, _payload(surface, path))
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        _record(surface, e, "makedirs", record)
        raise
    _record(surface, None, "makedirs", record)


def probe_writable(dirpath: str, surface: str, *,
                   record: bool = True) -> None:
    """Prove a directory is actually writable by writing and removing a
    probe file — the only reliable EROFS/ENOSPC detector for surfaces
    (XLA cache) whose writes happen inside a library we don't wrap."""
    probe = os.path.join(dirpath, ".kyverno-write-probe")
    try:
        global_faults.fire(SITE_STORAGE_WRITE, _payload(surface, probe))
        with open(probe, "w") as fh:
            fh.write("probe")
        os.remove(probe)
    except OSError as e:
        _record(surface, e, "probe", record)
        raise
    _record(surface, None, "probe", record)
