"""Micro-batching admission pipeline.

The traffic-facing counterpart of the scan engine's batch-first design:
concurrent AdmissionReviews coalesce into padded, shape-bucketed device
batches (one XLA program per bucket, reused across flushes), with
deadline-aware flushing, overload shedding, and per-request verdict
dispatch. See serving/batcher.py for the pipeline proper.
"""

from .batcher import AdmissionPipeline, BatchConfig
from .dispatch import resource_verdicts
from .queue import (AdmissionQueue, DeadlineExceededError, QueuedRequest,
                    QueueFullError)
from .scheduler import (ClassifyConfig, RequestClass, classify_request,
                        parse_class_weights)

__all__ = [
    "AdmissionPipeline",
    "AdmissionQueue",
    "BatchConfig",
    "ClassifyConfig",
    "DeadlineExceededError",
    "QueueFullError",
    "QueuedRequest",
    "RequestClass",
    "classify_request",
    "parse_class_weights",
    "resource_verdicts",
]
