"""AdmissionPipeline — coalesce concurrent requests into padded device
batches.

The pipeline sits between the HTTP admission handler and the batch
engine. A dedicated flusher thread drains the bounded queue when either
`max_batch_size` requests accumulate or the oldest entry has waited
`max_wait_ms` — flushing EARLY when an entry's deadline would otherwise
expire before the timer matures (deadline-aware flush). Each flush pads
the live requests up to a power-of-two bucket so the device program is
dispatched at one of O(log2) shapes: the XLA jit cache is keyed by
shape, so bucketed padding means batches of 3, 9, or 14 requests all
reuse the 16-wide compiled program instead of churning recompiles.

Overload policy: when the queue is at its high-water mark, submit()
sheds — either degrading the single request to the caller-supplied
scalar fallback (graceful degradation, verdicts still exact) or raising
QueueFullError for the handler to translate per failurePolicy. The
queue never blocks unboundedly.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability.metrics import MetricsRegistry, global_registry
from ..observability.profiling import (PATH_DEVICE, last_dispatch_path,
                                       set_dispatch_path)
from ..observability.tracing import global_tracer
from .queue import (AdmissionQueue, DeadlineExceededError, QueuedRequest,
                    QueueFullError)


@dataclass
class BatchConfig:
    max_batch_size: int = 64
    max_wait_ms: float = 2.0
    # total budget a request may spend queued; in-flight evaluation is
    # allowed to complete past it (eval_grace_s bounds the full wait)
    deadline_ms: float = 5000.0
    # how far BEFORE the oldest entry's deadline a deadline-triggered
    # flush fires: flushing at the deadline itself would drain an
    # already-expired entry that then never reaches the evaluator
    deadline_lead_ms: float = 2.0
    high_water: int = 1024
    shed_mode: str = "scalar"  # scalar | fail
    # smallest padded shape; callers wiring the pipeline to a TpuEngine
    # overwrite this with TpuEngine.MIN_BUCKET (webhooks/server.py,
    # bench.py) so the pipeline's padding and the engine's own
    # bucketing agree on the dispatched shape (no double padding).
    # serving/ stays jax-free, so the engine constant is not imported
    # here
    min_bucket: int = 16
    eval_grace_s: float = 30.0

    def bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return b


class AdmissionPipeline:
    """evaluate_fn(padded_payloads) -> per-payload results.

    `padded_payloads` is the drained batch padded with None up to the
    bucket size; evaluate_fn must return at least as many results as
    there are real (non-None) leading payloads. scalar_fallback(payload)
    -> result is the single-request degradation path used on shed."""

    def __init__(
        self,
        evaluate_fn: Callable[[List[Any]], List[Any]],
        scalar_fallback: Optional[Callable[[Any], Any]] = None,
        config: Optional[BatchConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        version_provider: Optional[Callable[[], Any]] = None,
        cache_lookup: Optional[Callable[[Any], Any]] = None,
        flight_hook: Optional[Callable[..., None]] = None,
    ) -> None:
        self._fn = evaluate_fn
        self._scalar = scalar_fallback
        # flight recorder (observability/flightrecorder.py): called
        # once per resolved request with (payload, result-or-exception,
        # path, latency_s, trace_id, timings). Batched requests are
        # recorded from the FLUSHER thread after every waiter is woken
        # — like span recording, the black box must not tax request
        # latency; cached/shed resolutions record at submit()
        self._flight = flight_hook
        # content-addressed fast path: when the caller supplies a
        # lookup (webhooks/server.py wires the verdict cache), a repeat
        # admission of an identical manifest resolves at submit() —
        # before the queue, before the flusher, before the device.
        # None = miss; the request then takes the normal batched path
        self._cache_lookup = cache_lookup
        # policy-set version pinning (lifecycle/): with a provider, the
        # flusher captures ONE compiled version per flush and hands it
        # to evaluate_fn(padded, version) — a hot swap landing mid-queue
        # affects the NEXT flush; no batch ever mixes revisions
        self._version_provider = version_provider
        self.config = config or BatchConfig()
        self.metrics = metrics or global_registry
        self.queue = AdmissionQueue(self.config.high_water)
        self._stopped = False
        self.stats: Dict[str, Any] = {
            "requests": 0, "flushes": 0, "evaluated": 0, "shed": 0,
            "expired": 0, "cache_hits": 0, "flush_reasons": {},
            "flushes_by_bucket": {}, "occupancy_sum": 0.0,
        }
        self._stats_lock = threading.Lock()
        self.metrics.serving_queue_depth.set(0)
        self._flusher = threading.Thread(target=self._run, daemon=True,
                                         name="admission-flusher")
        self._flusher.start()

    # -- caller side

    def submit(self, payload: Any, deadline_ms: Optional[float] = None,
               eval_grace_s: Optional[float] = None) -> Any:
        """``eval_grace_s`` caps how long a DISPATCHED request may wait
        past its queue budget for the evaluator; callers with a hard
        wall (the webhook's request timeout — the API server hangs up
        at timeoutSeconds regardless) pass their remaining budget so
        a wedged evaluator resolves per failurePolicy inside it instead
        of holding the connection for the full default grace."""
        if self._stopped:
            raise RuntimeError("admission pipeline is stopped")
        if self._cache_lookup is not None:
            t0 = time.monotonic()
            try:
                cached = self._cache_lookup(payload)
            except Exception:
                cached = None  # lookup failures take the normal path
            if cached is not None:
                with self._stats_lock:
                    self.stats["cache_hits"] = \
                        self.stats.get("cache_hits", 0) + 1
                dt = time.monotonic() - t0
                self.metrics.serving_request_latency.observe(
                    dt, {"path": "cached"})
                self._record_slo(dt)
                self._record_flight(payload, cached, "cached", dt, "")
                return cached
        budget = (deadline_ms if deadline_ms is not None
                  else self.config.deadline_ms) / 1000.0
        grace = (eval_grace_s if eval_grace_s is not None
                 else self.config.eval_grace_s)
        # ONE trace per request: the submit span is the root, its
        # context rides the queue entry across the flusher handoff, and
        # the latency histogram carries the trace id as an exemplar so a
        # slow bucket links back to a concrete trace (/debug/traces)
        with global_tracer.span("admission.submit") as root:
            exemplar = {"trace_id": root.trace_id}
            t0 = time.monotonic()
            try:
                req = self.queue.put(payload, t0 + budget, now=t0,
                                     trace_ctx=root.context)
            except QueueFullError:
                with self._stats_lock:
                    self.stats["shed"] += 1
                root.add_event("shed", depth=self.queue.high_water)
                if self.config.shed_mode == "scalar" and self._scalar is not None:
                    self.metrics.serving_shed_total.inc({"outcome": "scalar"})
                    with global_tracer.span("admission.scalar_fallback",
                                            parent=root.context,
                                            reason="shed"):
                        out = self._scalar(payload)
                    dt = time.monotonic() - t0
                    self.metrics.serving_request_latency.observe(
                        dt, {"path": "shed"}, exemplar=exemplar)
                    self._record_slo(dt)
                    self._record_flight(payload, out, "shed", dt,
                                        root.trace_id)
                    return out
                self.metrics.serving_shed_total.inc({"outcome": "rejected"})
                self._record_flight(payload, QueueFullError("shed"), "shed",
                                    time.monotonic() - t0, root.trace_id)
                raise
            self.metrics.serving_queue_depth.set(self.queue.depth())
            # the deadline governs QUEUE time; only a request that
            # actually made it onto the device earns eval_grace_s to
            # complete — a request still queued past its budget (wedged
            # flusher) resolves per failurePolicy NOW, honoring the
            # webhook's request timeout
            if not req.event.wait(budget):
                if not req.dispatched:
                    raise DeadlineExceededError(
                        "request deadline expired while queued")
                if not req.event.wait(grace):
                    raise DeadlineExceededError(
                        "admission batch evaluation timed out")
            dt = time.monotonic() - t0
            self.metrics.serving_request_latency.observe(
                dt, {"path": "batched"}, exemplar=exemplar)
            self._record_slo(dt)
            if isinstance(req.result, BaseException):
                raise req.result
            return req.result

    def _record_flight(self, payload: Any, result: Any, path: str,
                       latency_s: float, trace_id: str,
                       timings: Optional[Dict[str, float]] = None) -> None:
        if self._flight is None:
            return
        try:
            self._flight(payload, result, path, latency_s, trace_id,
                         timings)
        except Exception:
            pass  # the black box must never fail a request

    @staticmethod
    def _record_slo(latency_s: float) -> None:
        """Feed the admission-latency SLO window (every path a request
        can resolve through: batched, cached, shed-to-scalar)."""
        try:
            from ..observability.analytics import global_slo

            global_slo.record_admission(latency_s)
        except Exception:
            pass

    def stop(self) -> None:
        with self.queue.cv:
            self._stopped = True
            self.queue.closed = True
            self.queue.cv.notify_all()
        self._flusher.join(timeout=self.config.eval_grace_s)
        # the flusher's final drain normally empties the queue; if it
        # is wedged on a stuck evaluator (join timed out), whoever is
        # still QUEUED resolves now via the scalar fallback — shutdown
        # degrades service, it never strands a waiter unresolved
        for req in self.queue.drain_all():
            try:
                if self._scalar is None:
                    raise RuntimeError(
                        "admission pipeline stopped before evaluation")
                req.resolve(self._scalar(req.payload))
                self.metrics.serving_shed_total.inc({"outcome": "shutdown"})
            except BaseException as e:  # waiter gets the error, not a hang
                req.resolve(e)

    # -- flusher side

    def _run(self) -> None:
        cfg = self.config
        max_wait = cfg.max_wait_ms / 1000.0
        lead = cfg.deadline_lead_ms / 1000.0
        while True:
            with self.queue.cv:
                while True:
                    if self.queue.depth() >= cfg.max_batch_size:
                        reason = "size"
                        break
                    oldest = self.queue.oldest()
                    if self._stopped:
                        # final drain: anything still queued flushes now
                        # (an empty queue makes this a no-op exit)
                        reason = "shutdown"
                        break
                    if oldest is None:
                        t_w = time.monotonic()
                        self.queue.cv.wait()
                        self.metrics.serving_flusher_seconds.inc(
                            {"state": "wait_queue"}, time.monotonic() - t_w)
                        continue
                    now = time.monotonic()
                    # deadline-aware: flush when the timer matures OR —
                    # EARLY, with deadline_lead_ms to spare — when
                    # waiting for the timer would expire the oldest
                    # entry before it ever reaches the evaluator
                    timer_at = oldest.enqueued_at + max_wait
                    deadline_at = oldest.deadline - lead
                    flush_at = min(timer_at, deadline_at)
                    if now >= flush_at:
                        reason = "timer" if timer_at <= deadline_at \
                            else "deadline"
                        break
                    t_w = time.monotonic()
                    self.queue.cv.wait(flush_at - now)
                    self.metrics.serving_flusher_seconds.inc(
                        {"state": "wait_queue"}, time.monotonic() - t_w)
                batch = self.queue.drain(cfg.max_batch_size)
                drained_at = time.monotonic()
                stopped = self._stopped
            if batch:
                self._process(batch, reason, drained_at)
                self.metrics.serving_queue_depth.set(self.queue.depth())
            if stopped and not batch:
                return

    def _process(self, batch: List[QueuedRequest], reason: str,
                 now: Optional[float] = None) -> None:
        # expiry is judged at the moment the flush decision drained the
        # queue: a deadline-triggered flush fires deadline_lead_ms early
        # precisely so the entry it fires for is still live here, and
        # scheduling jitter between drain and this check must not
        # re-expire it (drained entries are marked dispatched under the
        # cv, so submit()'s wait has eval_grace_s slack for them)
        if now is None:
            now = time.monotonic()
        # queue-wait spans materialize HERE, retroactively, in each
        # request's own trace: the flusher owns the drain timestamp and
        # the queue entry carried the submit span's context over
        for req in batch:
            if req.trace_ctx is not None:
                global_tracer.record_span(
                    "admission.queue_wait", req.enqueued_at,
                    req.drained_at or now, parent=req.trace_ctx,
                    flush_reason=reason)
        # queue-occupancy attribution: aggregate request-seconds spent
        # queued, scrapeable next to the flusher's own state split
        self.metrics.serving_flusher_seconds.inc(
            {"state": "request_queue_wait"},
            sum(max(0.0, (req.drained_at or now) - req.enqueued_at)
                for req in batch))
        live: List[QueuedRequest] = []
        for req in batch:
            if req.deadline <= now:
                # expired mid-queue: resolve with the error instead of
                # spending device work on a verdict nobody is waiting for
                err = DeadlineExceededError(
                    "request deadline expired while queued")
                req.resolve(err)
                self._record_flight(
                    req.payload, err, "batched", now - req.enqueued_at,
                    req.trace_ctx.trace_id if req.trace_ctx else "")
            else:
                live.append(req)
        n_expired = len(batch) - len(live)
        if n_expired:
            self.metrics.serving_deadline_expired_total.inc(value=n_expired)
        bucket = self.config.bucket(len(live)) if live else 0
        with self._stats_lock:
            self.stats["requests"] += len(batch)
            self.stats["expired"] += n_expired
            self.stats["flushes"] += 1
            reasons = self.stats["flush_reasons"]
            reasons[reason] = reasons.get(reason, 0) + 1
            if live:
                by_bucket = self.stats["flushes_by_bucket"]
                by_bucket[bucket] = by_bucket.get(bucket, 0) + 1
                self.stats["evaluated"] += len(live)
                self.stats["occupancy_sum"] += len(live) / bucket
        self.metrics.serving_flush_total.inc({"reason": reason})
        if not live:
            return
        self.metrics.serving_batch_size.observe(len(live))
        self.metrics.serving_batch_occupancy.observe(len(live) / bucket)
        padded = [req.payload for req in live] + [None] * (bucket - len(live))
        # pin the compiled policy-set version for this WHOLE flush
        # before evaluation: every request drained into this batch
        # evaluates against exactly this version, even if a hot swap
        # promotes a newer one while the batch is on the device
        pin = None
        if self._version_provider is not None:
            try:
                pin = self._version_provider()
            except BaseException:
                pin = None  # evaluator owns the unavailability ladder
        pin_rev = getattr(pin, "revision", None)
        if pin_rev is not None:
            with self._stats_lock:
                self.stats["last_flush_revision"] = pin_rev
        t_eval0 = time.monotonic()
        set_dispatch_path(PATH_DEVICE)  # evaluator overwrites on fallback
        try:
            # chaos hook: an armed serving.flush fault lands here, so
            # an injected flush failure takes the SAME path a real
            # evaluator error takes — every waiter gets the exception
            # and the webhook layer resolves it per failurePolicy
            from ..resilience.faults import SITE_SERVING_FLUSH, global_faults

            global_faults.fire(SITE_SERVING_FLUSH)
            results = (self._fn(padded) if self._version_provider is None
                       else self._fn(padded, pin))
            if len(results) < len(live):
                raise RuntimeError("batch evaluator returned wrong arity")
        except BaseException as e:  # propagate to every waiter
            t_eval1 = time.monotonic()
            self.metrics.serving_flusher_seconds.inc(
                {"state": "evaluate"}, t_eval1 - t_eval0)
            for req in live:
                req.resolve(e)
            self._record_flush_spans(live, reason, bucket, now, t_eval0,
                                     t_eval1, error=f"{type(e).__name__}: {e}",
                                     revision=pin_rev)
            for req in live:
                self._record_flight(
                    req.payload, e, "batched", t_eval1 - req.enqueued_at,
                    req.trace_ctx.trace_id if req.trace_ctx else "",
                    {"eval_s": t_eval1 - t_eval0})
            return
        t_eval1 = time.monotonic()
        self.metrics.serving_flusher_seconds.inc(
            {"state": "evaluate"}, t_eval1 - t_eval0)
        t_resolve0 = time.monotonic()
        for req, result in zip(live, results):
            req.resolve(result)
        t_resolve1 = time.monotonic()
        self.metrics.serving_flusher_seconds.inc(
            {"state": "resolve"}, t_resolve1 - t_resolve0)
        # span recording (and any exporter I/O it triggers) happens
        # AFTER every waiter is woken: the spans carry explicit
        # timestamps, so ordering costs nothing — doing it first would
        # tax every request's latency with tracing overhead
        self._record_flush_spans(live, reason, bucket, now, t_eval0, t_eval1,
                                 revision=pin_rev)
        for req in live:
            if req.trace_ctx is not None:
                global_tracer.record_span(
                    "admission.verdict_dispatch", t_resolve0, t_resolve1,
                    parent=req.trace_ctx, batch_size=len(live))
        if self._flight is not None:
            # AFTER the waiters are resolved and the spans recorded:
            # the flusher thread still holds the dispatch-path thread-
            # local, so the hook can classify device vs fallback
            eval_s = t_eval1 - t_eval0
            for req, result in zip(live, results):
                self._record_flight(
                    req.payload, result, "batched",
                    t_resolve1 - req.enqueued_at,
                    req.trace_ctx.trace_id if req.trace_ctx else "",
                    {"queue_wait_s": max(0.0, (req.drained_at or now)
                                         - req.enqueued_at),
                     "eval_s": eval_s})

    def _record_flush_spans(self, live: List[QueuedRequest], reason: str,
                            bucket: int, drained_at: float,
                            t_eval0: float, t_eval1: float,
                            error: Optional[str] = None,
                            revision: Optional[int] = None) -> None:
        """Per-request flush + dispatch spans: the batch evaluation is
        shared work, but each request's trace must tell the whole story,
        so the shared timings are recorded once per participating trace
        — named by HOW the batch actually resolved (the engine marks the
        device-vs-scalar path in a thread-local this flusher thread
        reads back). With ``error`` set (the evaluator raised), the
        flush span records the failure and no dispatch span is emitted —
        nothing dispatched."""
        traced = [r for r in live if r.trace_ctx is not None]
        if not traced:
            return
        rev_attr = {} if revision is None else {"policyset_revision": revision}
        if error is not None:
            for req in traced:
                global_tracer.record_span(
                    "admission.flush", req.drained_at or drained_at, t_eval1,
                    parent=req.trace_ctx, status="error", reason=reason,
                    batch_size=len(live), bucket=bucket, error=error,
                    **rev_attr)
            return
        path = last_dispatch_path()
        dispatch_name = ("admission.device_dispatch" if path == PATH_DEVICE
                         else "admission.scalar_fallback")
        try:
            from ..resilience.breaker import tpu_breaker

            breaker_state = tpu_breaker().state
        except Exception:
            breaker_state = "unknown"
        for req in traced:
            global_tracer.record_span(
                "admission.flush", req.drained_at or drained_at, t_eval1,
                parent=req.trace_ctx, reason=reason, batch_size=len(live),
                bucket=bucket, **rev_attr)
            global_tracer.record_span(
                dispatch_name, t_eval0, t_eval1, parent=req.trace_ctx,
                engine=path, breaker=breaker_state, batch_size=len(live))

    # -- introspection

    def mean_batch_size(self) -> float:
        with self._stats_lock:
            flushes = sum(self.stats["flushes_by_bucket"].values())
            return self.stats["evaluated"] / flushes if flushes else 0.0

    def state(self) -> Dict[str, Any]:
        """JSON-ready snapshot for /debug/state: queue pressure, bucket
        occupancy, flush accounting."""
        with self._stats_lock:
            stats = {k: (dict(v) if isinstance(v, dict) else v)
                     for k, v in self.stats.items()}
        flushes = sum(stats["flushes_by_bucket"].values())
        return {
            "queue_depth": self.queue.depth(),
            "high_water": self.queue.high_water,
            "stopped": self._stopped,
            "mean_batch_size": round(
                stats["evaluated"] / flushes, 3) if flushes else 0.0,
            "mean_occupancy": round(
                stats["occupancy_sum"] / flushes, 3) if flushes else 0.0,
            "config": {
                "max_batch_size": self.config.max_batch_size,
                "max_wait_ms": self.config.max_wait_ms,
                "deadline_ms": self.config.deadline_ms,
                "min_bucket": self.config.min_bucket,
                "shed_mode": self.config.shed_mode,
            },
            "stats": stats,
        }
