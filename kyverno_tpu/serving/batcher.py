"""AdmissionPipeline — coalesce concurrent requests into padded device
batches, scheduled by class.

The pipeline sits between the HTTP admission handler and the batch
engine. A dedicated flusher thread drains the class-aware queue when a
flush trigger matures — `max_batch_size` requests accumulated, the
oldest non-bulk entry waited `max_wait_ms`, the oldest bulk entry
waited `bulk_max_wait_ms` (the coalescing window), or an entry's
deadline would otherwise expire before any timer (deadline-aware
flush). Each flush pads the live requests up to a power-of-two bucket
so the device program is dispatched at one of O(log2) shapes: the XLA
jit cache is keyed by shape, so bucketed padding means batches of 3,
9, or 14 requests all reuse the 16-wide compiled program instead of
churning recompiles.

Scheduling (serving/queue.py + serving/scheduler.py): requests carry a
(tenant x operation x priority) class; flush composition takes urgent
(deadline-imminent) entries first, then weighted-fair order across the
non-bulk classes, and bulk traffic only when its own timer matured or
as free riders filling the flush up to its shape bucket.

Overload policy, a ladder that degrades BY CLASS instead of uniformly:

- burn-driven admission control: when the admission-latency SLO burn
  rate (observability/analytics.py SloTracker) crosses a class's
  threshold, that class sheds at submit() — bulk first, then default;
  the critical tier is never burn-shed;
- class queue shares: bulk is capped at `bulk_share` of the queue and
  the top `critical_reserve` fraction only admits critical requests;
- the global high-water mark refuses everyone (the classic backstop).

A shed either degrades to the caller-supplied scalar fallback
(graceful, verdicts still exact) or raises QueueFullError for the
handler to translate per failurePolicy; the queue never blocks
unboundedly. Separately, hedged scalar dispatch races the scalar
oracle against an in-flight device batch for any dispatched request
whose remaining deadline budget falls below `hedge_threshold` — first
resolution wins (bit-identical either way), the loser's result is
discarded, and the race lands in the flight ring.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..observability.metrics import MetricsRegistry, global_registry
from ..observability.profiling import (PATH_DEVICE, last_dispatch_path,
                                       set_dispatch_path)
from ..observability.tracing import global_tracer
from .queue import (PIN_PENDING, AdmissionQueue, DeadlineExceededError,
                    QueuedRequest, QueueFullError)
from .scheduler import (DEFAULT_CLASS_WEIGHTS, burn_shed_threshold,
                        priority_of)


@dataclass
class BatchConfig:
    max_batch_size: int = 64
    max_wait_ms: float = 2.0
    # total budget a request may spend queued; in-flight evaluation is
    # allowed to complete past it (eval_grace_s bounds the full wait)
    deadline_ms: float = 5000.0
    # how far BEFORE the oldest entry's deadline a deadline-triggered
    # flush fires: flushing at the deadline itself would drain an
    # already-expired entry that then never reaches the evaluator
    deadline_lead_ms: float = 2.0
    high_water: int = 1024
    shed_mode: str = "scalar"  # scalar | fail
    # smallest padded shape; callers wiring the pipeline to a TpuEngine
    # overwrite this with TpuEngine.MIN_BUCKET (webhooks/server.py,
    # bench.py) so the pipeline's padding and the engine's own
    # bucketing agree on the dispatched shape (no double padding).
    # serving/ stays jax-free, so the engine constant is not imported
    # here
    min_bucket: int = 16
    eval_grace_s: float = 30.0
    # -- class scheduling (serving/scheduler.py)
    # weighted-fair share per priority tier; each (tenant, operation,
    # priority) class is its own flow weighted by its tier
    class_weights: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_CLASS_WEIGHTS))
    # bulk coalescing window: bulk entries wait up to this long to fill
    # whole shape buckets instead of riding (and fragmenting) every
    # max_wait_ms flush
    bulk_max_wait_ms: float = 50.0
    # entries whose remaining deadline drops below this ride the next
    # flush regardless of class credit (never below deadline_lead_ms)
    urgent_ms: float = 10.0
    # hedged scalar dispatch: once a DISPATCHED request's remaining
    # deadline budget falls below this fraction while its device batch
    # is still in flight, the submitting thread races the scalar oracle
    # against the batch (0 disables; needs a scalar fallback)
    hedge_threshold: float = 0.0
    # burn-driven shed ladder: admission-latency SLO burn rate above
    # which the tier sheds at submit() (0 disables a rung). Bulk sheds
    # first; critical is never burn-shed.
    shed_burn_bulk: float = 1.0
    shed_burn_default: float = 0.0
    # class queue shares: bulk may occupy at most bulk_share of the
    # queue; the top critical_reserve fraction admits only critical
    bulk_share: float = 0.5
    critical_reserve: float = 0.1
    # shed mode override for the bulk tier (None = shed_mode): bulk
    # floods usually want "fail" — resolve per failurePolicy instead of
    # spending scalar-oracle work on traffic that is being shed
    bulk_shed_mode: Optional[str] = None

    def bucket(self, n: int) -> int:
        b = self.min_bucket
        while b < n:
            b *= 2
        return b

    def shed_mode_for(self, cls: Any) -> str:
        if priority_of(cls) == "bulk" and self.bulk_shed_mode:
            return self.bulk_shed_mode
        return self.shed_mode


class AdmissionPipeline:
    """evaluate_fn(padded_payloads) -> per-payload results.

    `padded_payloads` is the drained batch padded with None up to the
    bucket size; evaluate_fn must return at least as many results as
    there are real (non-None) leading payloads. scalar_fallback(payload)
    -> result is the single-request degradation path used on shed."""

    def __init__(
        self,
        evaluate_fn: Callable[[List[Any]], List[Any]],
        scalar_fallback: Optional[Callable[[Any], Any]] = None,
        config: Optional[BatchConfig] = None,
        metrics: Optional[MetricsRegistry] = None,
        version_provider: Optional[Callable[[], Any]] = None,
        cache_lookup: Optional[Callable[[Any], Any]] = None,
        flight_hook: Optional[Callable[..., None]] = None,
        hedge_fn: Optional[Callable[[Any, Any], Any]] = None,
        burn_provider: Optional[Callable[[], float]] = None,
    ) -> None:
        self._fn = evaluate_fn
        self._scalar = scalar_fallback
        # hedged dispatch path: hedge_fn(payload, pinned_version) must
        # produce the SAME rows the racing device batch would (webhooks
        # wire the scalar oracle pinned at the flush's revision); bare
        # scalar fallbacks that ignore the version work too
        self._hedge = hedge_fn if hedge_fn is not None else (
            None if scalar_fallback is None
            else (lambda payload, version: scalar_fallback(payload)))
        # SLO burn signal for the shed ladder (default: the process
        # SloTracker's cached short-window admission burn rate)
        self._burn_provider = burn_provider
        # flight recorder (observability/flightrecorder.py): called
        # once per resolved request with (payload, result-or-exception,
        # path, latency_s, trace_id, timings). Batched requests are
        # recorded from the FLUSHER thread after every waiter is woken
        # — like span recording, the black box must not tax request
        # latency; cached/shed resolutions record at submit()
        self._flight = flight_hook
        # content-addressed fast path: when the caller supplies a
        # lookup (webhooks/server.py wires the verdict cache), a repeat
        # admission of an identical manifest resolves at submit() —
        # before the queue, before the flusher, before the device.
        # None = miss; the request then takes the normal batched path
        self._cache_lookup = cache_lookup
        # policy-set version pinning (lifecycle/): with a provider, the
        # flusher captures ONE compiled version per flush and hands it
        # to evaluate_fn(padded, version) — a hot swap landing mid-queue
        # affects the NEXT flush; no batch ever mixes revisions
        self._version_provider = version_provider
        self.config = config or BatchConfig()
        self.metrics = metrics or global_registry
        self.queue = AdmissionQueue(self.config.high_water,
                                    config=self.config)
        self._stopped = False
        # guarded-by: _stats_lock
        self.stats: Dict[str, Any] = {
            "requests": 0, "flushes": 0, "evaluated": 0, "shed": 0,
            "expired": 0, "cache_hits": 0, "flush_reasons": {},
            "flushes_by_bucket": {}, "occupancy_sum": 0.0,
            "by_class": {}, "hedges": 0, "hedge_wins_scalar": 0,
            "hedge_wins_device": 0, "hedge_lost_to_error": 0,
            "hedge_lost_to_expiry": 0,
            "hedge_errors": 0, "bulk_topups": 0,
        }
        self._stats_lock = threading.Lock()
        self.metrics.serving_queue_depth.set(0)
        self._flusher = threading.Thread(target=self._run, daemon=True,
                                         name="admission-flusher")
        self._flusher.start()

    # -- caller side

    def submit(self, payload: Any, deadline_ms: Optional[float] = None,
               eval_grace_s: Optional[float] = None, cls: Any = None) -> Any:
        """``eval_grace_s`` caps how long a DISPATCHED request may wait
        past its queue budget for the evaluator; callers with a hard
        wall (the webhook's request timeout — the API server hangs up
        at timeoutSeconds regardless) pass their remaining budget so
        a wedged evaluator resolves per failurePolicy inside it instead
        of holding the connection for the full default grace. ``cls``
        is the request's scheduling class (scheduler.classify_request);
        unclassified requests ride the default tier."""
        if self._stopped:
            raise RuntimeError("admission pipeline is stopped")
        pri = priority_of(cls)
        # ONE trace per request: the submit span is the root, its
        # context rides the queue entry across the flusher handoff, and
        # the latency histogram carries the trace id as an exemplar so a
        # slow bucket links back to a concrete trace (/debug/traces).
        # The span opens BEFORE the cache lookup: a lookup that falls
        # through to a peer fetch carries this context on the wire, so
        # a peer-served admission is one connected cross-replica trace
        # — and even a pure cache hit gets a trace id in its flight
        # record.
        with global_tracer.span("admission.submit") as root:
            exemplar = {"trace_id": root.trace_id}
            if self._cache_lookup is not None:
                t0 = time.monotonic()
                try:
                    cached = self._cache_lookup(payload)
                except Exception:
                    cached = None  # lookup failures take the normal path
                if cached is not None:
                    with self._stats_lock:
                        self.stats["cache_hits"] = \
                            self.stats.get("cache_hits", 0) + 1
                        self._cstat_locked(pri)["cache_hits"] += 1
                    dt = time.monotonic() - t0
                    self.metrics.serving_request_latency.observe(
                        dt, {"path": "cached", "class": pri},
                        exemplar=exemplar)
                    self.metrics.serving_class_requests.inc(
                        {"class": pri, "outcome": "cached"})
                    self._record_slo(dt, pri)
                    self._record_flight(payload, cached, "cached", dt,
                                        root.trace_id)
                    return cached
            budget = (deadline_ms if deadline_ms is not None
                      else self.config.deadline_ms) / 1000.0
            grace = (eval_grace_s if eval_grace_s is not None
                     else self.config.eval_grace_s)
            t0 = time.monotonic()
            # burn-driven admission control BEFORE the queue: a class
            # past its burn threshold sheds now — bulk first (lowest
            # threshold), default above it, critical never
            thr = burn_shed_threshold(self.config, cls)
            if thr > 0 and self._burn() > thr:
                return self._shed(payload, cls, "burn", root, exemplar, t0)
            try:
                req = self.queue.put(payload, t0 + budget, now=t0,
                                     trace_ctx=root.context, cls=cls)
            except QueueFullError as e:
                return self._shed(payload, cls, e.reason, root, exemplar,
                                  t0, err=e)
            self.metrics.serving_queue_depth.set(self.queue.depth())
            self._publish_class_depths()
            # the deadline governs QUEUE time; only a request that
            # actually made it onto the device earns eval_grace_s to
            # complete — a request still queued past its budget (wedged
            # flusher) resolves per failurePolicy NOW, honoring the
            # webhook's request timeout
            resolved = self._wait_with_hedge(req, payload, budget, root)
            if not resolved:
                if not req.dispatched:
                    # still queued: the flusher will drain this entry
                    # later and count its expiry — counting here too
                    # would double it
                    raise DeadlineExceededError(
                        "request deadline expired while queued")
                if not req.event.wait(grace):
                    # dispatched but the evaluator outran the grace:
                    # the flusher's eventual resolve goes unread, so
                    # this is the only place the outcome can count
                    self.metrics.serving_class_requests.inc(
                        {"class": pri, "outcome": "expired"})
                    raise DeadlineExceededError(
                        "admission batch evaluation timed out")
            dt = time.monotonic() - t0
            path = "hedged" if req.winner == "hedge_scalar" else "batched"
            self.metrics.serving_request_latency.observe(
                dt, {"path": path, "class": pri}, exemplar=exemplar)
            self._record_slo(dt, pri)
            if isinstance(req.result, BaseException):
                # one outcome per request: mid-queue expiries were
                # already counted "expired" by the flusher; anything
                # else resolved-with-error is an evaluator failure
                if not isinstance(req.result, DeadlineExceededError):
                    self.metrics.serving_class_requests.inc(
                        {"class": pri, "outcome": "error"})
                raise req.result
            self.metrics.serving_class_requests.inc(
                {"class": pri, "outcome": path})
            return req.result

    # -- overload ladder (shed) and hedged dispatch

    def _cstat_locked(self, pri: str) -> Dict[str, int]:
        """Per-class stats bucket; callers hold _stats_lock."""
        c = self.stats["by_class"].get(pri)
        if c is None:
            c = {"requests": 0, "evaluated": 0, "shed": 0, "expired": 0,
                 "cache_hits": 0, "hedges": 0}
            self.stats["by_class"][pri] = c
        return c

    def _burn(self) -> float:
        if self._burn_provider is not None:
            try:
                return float(self._burn_provider())
            except Exception:
                return 0.0
        try:
            from ..observability.analytics import global_slo

            return global_slo.admission_burn_fast()
        except Exception:
            return 0.0

    def _publish_class_depths(self) -> None:
        try:
            depths = self.queue.depth_by_class()
            for pri in ("critical", "default", "bulk"):
                self.metrics.serving_class_queue_depth.set(
                    depths.get(pri, 0), {"class": pri})
        except Exception:
            pass

    def _shed(self, payload: Any, cls: Any, reason: str, root, exemplar,
              t0: float, err: Optional[BaseException] = None) -> Any:
        """One shed decision: degrade to the scalar fallback (verdicts
        still exact) or raise for the handler to translate per
        failurePolicy — per the CLASS's shed mode (bulk floods usually
        fail fast; critical sheds prefer the exact scalar path)."""
        pri = priority_of(cls)
        with self._stats_lock:
            self.stats["shed"] += 1
            self._cstat_locked(pri)["shed"] += 1
        root.add_event("shed", reason=reason, cls=pri,
                       depth=self.queue.depth())
        self.metrics.serving_class_requests.inc(
            {"class": pri, "outcome": "shed"})
        mode = self.config.shed_mode_for(cls)
        if mode == "scalar" and self._scalar is not None:
            self.metrics.serving_shed_total.inc(
                {"outcome": "scalar", "class": pri, "reason": reason})
            with global_tracer.span("admission.scalar_fallback",
                                    parent=root.context, reason=reason):
                out = self._scalar(payload)
            dt = time.monotonic() - t0
            self.metrics.serving_request_latency.observe(
                dt, {"path": "shed", "class": pri}, exemplar=exemplar)
            self._record_slo(dt, pri)
            self._record_flight(payload, out, "shed", dt, root.trace_id)
            return out
        self.metrics.serving_shed_total.inc(
            {"outcome": "rejected", "class": pri, "reason": reason})
        e = err if err is not None else QueueFullError(
            f"shed ({reason}, class={pri})", reason=reason)
        self._record_flight(payload, e, "shed",
                            time.monotonic() - t0, root.trace_id)
        raise e

    def _wait_with_hedge(self, req: QueuedRequest, payload: Any,
                         budget: float, root) -> bool:
        """Wait out the queue budget; with hedging enabled, once the
        remaining budget falls below ``hedge_threshold`` and the
        request is DISPATCHED (its device batch is in flight), race the
        scalar oracle against the batch. Returns whether the request
        resolved inside the budget."""
        frac = self.config.hedge_threshold
        if frac <= 0 or self._hedge is None:
            return req.event.wait(budget)
        first = max(0.0, budget * (1.0 - min(frac, 1.0)))
        if req.event.wait(first):
            return True
        # the hedge condition is CONTINUOUS, not a single sample: under
        # overload — the very scenario hedging targets — the request is
        # often still QUEUED when the threshold trips (queue wait ate
        # the budget), and it gets dispatched moments later with almost
        # nothing left. Poll until the flush owns it, then race. The
        # race also waits for the flush to ASSIGN the pin: dispatched
        # flips at drain, but the pinned version lands a little later
        # in _process — racing inside that window would evaluate the
        # hedge at whatever revision is live, not the batch's, and a
        # hot swap could then make the "bit-identical" race lie. A
        # None pin (pure-scalar ladder / no version provider) is fine:
        # the hedge fn resolves the revision the same way the flush
        # evaluator will.
        while not req.event.is_set():
            remaining = req.deadline - time.monotonic()
            if remaining <= 0:
                break
            if req.dispatched and (self._version_provider is None
                                   or req.pin is not PIN_PENDING):
                self._hedge_race(req, payload, root)
                break
            req.event.wait(min(0.005, remaining))
        # the remaining wait is DEADLINE-relative, not (budget - first):
        # time spent inside the hedge race (a slow or fault-delayed
        # oracle) must come out of the request's own budget, or hedging
        # would hold the caller past the wall it exists to protect
        return req.event.wait(max(0.0, req.deadline - time.monotonic()))

    def _hedge_race(self, req: QueuedRequest, payload: Any, root) -> None:
        """The submitting thread (otherwise just blocked) evaluates the
        request through the scalar oracle at the revision its flush
        pinned and races the in-flight device batch: first resolution
        wins, the loser's bit-identical result is discarded, and the
        race is recorded in the flight ring with the winning path."""
        pri = priority_of(req.cls)
        with self._stats_lock:
            self.stats["hedges"] += 1
            self._cstat_locked(pri)["hedges"] += 1
        req.hedged = True
        # claim the flight record UP FRONT: a race that runs to
        # completion must be the one to record (labeled with its
        # winner), even when the flush's own record loop runs while
        # the oracle is still evaluating. A failed claim means the
        # request was already recorded (expired at drain, or the
        # flush raced ahead) — then whatever we produce goes
        # unrecorded, never double-recorded.
        owns = req.claim_flight()
        try:
            # chaos hook: serving.hedge faults land here, so an
            # injected hedge failure degrades to plain waiting on the
            # device batch — hedging must never make a request worse
            from ..resilience.faults import SITE_SERVING_HEDGE, global_faults

            global_faults.fire(SITE_SERVING_HEDGE)
            pin = None if req.pin is PIN_PENDING else req.pin
            with global_tracer.span("admission.hedge_dispatch",
                                    parent=root.context, cls=pri):
                out = self._hedge(payload, pin)
        except Exception:
            with self._stats_lock:
                self.stats["hedge_errors"] += 1
            self.metrics.serving_hedge.inc({"winner": "error"})
            if owns:
                # nothing to record: hand the claim back so the flush
                # records normally — and if the flush ALREADY resolved
                # (its record loop lost the claim to us and skipped),
                # re-claim and write the record ourselves, or the
                # request would vanish from the ring
                req.release_flight()
                if req.event.is_set() and req.claim_flight():
                    self._record_flight(
                        payload, req.result, "batched",
                        time.monotonic() - req.enqueued_at, root.trace_id)
            return
        if req.resolve(out, winner="hedge_scalar"):
            with self._stats_lock:
                self.stats["hedge_wins_scalar"] += 1
            self.metrics.serving_hedge.inc({"winner": "scalar"})
            root.add_event("hedge_won", winner="scalar")
            if owns:
                self._record_flight(
                    payload, out, "hedged_scalar",
                    time.monotonic() - req.enqueued_at, root.trace_id)
        elif isinstance(req.result, DeadlineExceededError):
            # the flush expired this request (deadline passed at drain)
            # while the oracle ran: no device batch raced at all, so
            # neither "device" nor "device_error" is true — the expiry
            # stood and the hedge's verdict arrived too late
            with self._stats_lock:
                self.stats["hedge_lost_to_expiry"] += 1
            self.metrics.serving_hedge.inc({"winner": "expired"})
            root.add_event("hedge_lost", winner="expired")
            if owns:
                self._record_flight(
                    payload, req.result, "hedged_expired",
                    time.monotonic() - req.enqueued_at, root.trace_id)
        elif isinstance(req.result, BaseException):
            # the flush resolved this request with an evaluator ERROR
            # before the oracle finished: the device did not "win" —
            # its batch failed, and the hedge's valid verdict arrived
            # too late to rescue the already-woken waiter. Count and
            # record that truthfully (operators reading the ring during
            # an incident must not see "device won" over an exception).
            with self._stats_lock:
                self.stats["hedge_lost_to_error"] += 1
            self.metrics.serving_hedge.inc({"winner": "device_error"})
            root.add_event("hedge_lost", winner="device_error")
            if owns:
                self._record_flight(
                    payload, req.result, "hedged_device_error",
                    time.monotonic() - req.enqueued_at,
                    root.trace_id)
        else:
            # device landed first while the oracle ran: ours is the
            # discarded (bit-identical) loser — record the race
            with self._stats_lock:
                self.stats["hedge_wins_device"] += 1
            self.metrics.serving_hedge.inc({"winner": "device"})
            root.add_event("hedge_lost", winner="device")
            if owns:
                self._record_flight(
                    payload, req.result, "hedged_device",
                    time.monotonic() - req.enqueued_at,
                    root.trace_id)

    def _record_flight(self, payload: Any, result: Any, path: str,
                       latency_s: float, trace_id: str,
                       timings: Optional[Dict[str, float]] = None) -> None:
        if self._flight is None:
            return
        try:
            self._flight(payload, result, path, latency_s, trace_id,
                         timings)
        except Exception:
            pass  # the black box must never fail a request

    @staticmethod
    def _record_slo(latency_s: float, cls: Any = None) -> None:
        """Feed the admission-latency SLO window (every path a request
        can resolve through: batched, cached, hedged, shed-to-scalar)
        — per class, so the per-class burn windows see the split."""
        try:
            from ..observability.analytics import global_slo

            global_slo.record_admission(latency_s, cls=priority_of(cls))
        except Exception:
            pass

    def stop(self) -> None:
        with self.queue.cv:
            self._stopped = True
            self.queue.closed = True
            self.queue.cv.notify_all()
        self._flusher.join(timeout=self.config.eval_grace_s)
        # the flusher's final drain normally empties the queue; if it
        # is wedged on a stuck evaluator (join timed out), whoever is
        # still QUEUED resolves now via the scalar fallback — in
        # priority order (drain_all sorts critical first), so shutdown
        # degrades service by class and never strands a waiter
        for req in self.queue.drain_all():
            try:
                if self._scalar is None:
                    raise RuntimeError(
                        "admission pipeline stopped before evaluation")
                req.resolve(self._scalar(req.payload))
                self.metrics.serving_shed_total.inc(
                    {"outcome": "shutdown",
                     "class": priority_of(req.cls)})
            except BaseException as e:  # waiter gets the error, not a hang
                req.resolve(e)

    # -- flusher side

    def _run(self) -> None:
        cfg = self.config
        while True:
            with self.queue.cv:
                while True:
                    if self.queue.depth() >= cfg.max_batch_size:
                        reason = "size"
                        break
                    if self._stopped:
                        # final drain: anything still queued flushes now
                        # (an empty queue makes this a no-op exit)
                        reason = "shutdown"
                        break
                    # class-aware flush triggers: the oldest non-bulk
                    # entry's timer, the oldest bulk entry's (longer)
                    # coalescing timer, and — EARLY, with
                    # deadline_lead_ms to spare — the tightest entry
                    # deadline, which would otherwise expire before any
                    # timer delivered it to the evaluator
                    times = self.queue.wake_times(cfg)
                    if not times:
                        t_w = time.monotonic()
                        self.queue.cv.wait()
                        self.metrics.serving_flusher_seconds.inc(
                            {"state": "wait_queue"}, time.monotonic() - t_w)
                        continue
                    now = time.monotonic()
                    flush_at = min(times.values())
                    if now >= flush_at:
                        # tie-break precedence mirrors the classic
                        # single-FIFO labels: timer before deadline,
                        # bulk's own window last
                        for label in ("timer", "deadline", "bulk_timer"):
                            if times.get(label) == flush_at:
                                reason = label
                                break
                        break
                    t_w = time.monotonic()
                    self.queue.cv.wait(flush_at - now)
                    self.metrics.serving_flusher_seconds.inc(
                        {"state": "wait_queue"}, time.monotonic() - t_w)
                batch = self.queue.drain(cfg.max_batch_size, config=cfg,
                                         stopping=self._stopped)
                drain_info = dict(self.queue.last_drain_info)
                drained_at = time.monotonic()
                stopped = self._stopped
            if batch:
                self._process(batch, reason, drained_at,
                              drain_info=drain_info)
                self.metrics.serving_queue_depth.set(self.queue.depth())
                self._publish_class_depths()
            if stopped and not batch:
                return

    def _process(self, batch: List[QueuedRequest], reason: str,
                 now: Optional[float] = None,
                 drain_info: Optional[Dict[str, Any]] = None) -> None:
        # expiry is judged at the moment the flush decision drained the
        # queue: a deadline-triggered flush fires deadline_lead_ms early
        # precisely so the entry it fires for is still live here, and
        # scheduling jitter between drain and this check must not
        # re-expire it (drained entries are marked dispatched under the
        # cv, so submit()'s wait has eval_grace_s slack for them)
        if now is None:
            now = time.monotonic()
        # queue-wait spans materialize HERE, retroactively, in each
        # request's own trace: the flusher owns the drain timestamp and
        # the queue entry carried the submit span's context over
        for req in batch:
            if req.trace_ctx is not None:
                global_tracer.record_span(
                    "admission.queue_wait", req.enqueued_at,
                    req.drained_at or now, parent=req.trace_ctx,
                    flush_reason=reason)
        # queue-occupancy attribution: aggregate request-seconds spent
        # queued, scrapeable next to the flusher's own state split
        self.metrics.serving_flusher_seconds.inc(
            {"state": "request_queue_wait"},
            sum(max(0.0, (req.drained_at or now) - req.enqueued_at)
                for req in batch))
        live: List[QueuedRequest] = []
        expired_ids = set()
        for req in batch:
            if req.deadline <= now:
                # expired mid-queue: resolve with the error instead of
                # spending device work on a verdict nobody is waiting for
                err = DeadlineExceededError(
                    "request deadline expired while queued")
                if req.resolve(err):
                    expired_ids.add(id(req))
                    self.metrics.serving_class_requests.inc(
                        {"class": priority_of(req.cls),
                         "outcome": "expired"})
                    if req.claim_flight():
                        self._record_flight(
                            req.payload, err, "batched",
                            now - req.enqueued_at,
                            req.trace_ctx.trace_id if req.trace_ctx else "")
                # else: a hedge race already resolved it — the hedge's
                # verdict stands and its accounting owns the outcome
                # (counting "expired" here too would double-count the
                # request: one outcome per request)
            else:
                live.append(req)
        n_expired = len(expired_ids)
        if n_expired:
            self.metrics.serving_deadline_expired_total.inc(value=n_expired)
        bucket = self.config.bucket(len(live)) if live else 0
        with self._stats_lock:
            self.stats["requests"] += len(batch)
            self.stats["expired"] += n_expired
            self.stats["flushes"] += 1
            reasons = self.stats["flush_reasons"]
            reasons[reason] = reasons.get(reason, 0) + 1
            if drain_info:
                self.stats["bulk_topups"] += drain_info.get("bulk_topup", 0)
            for req in batch:
                c = self._cstat_locked(priority_of(req.cls))
                c["requests"] += 1
                if id(req) in expired_ids:
                    c["expired"] += 1
                elif req.deadline > now:
                    c["evaluated"] += 1
                # hedge-rescued past-deadline entries count neither:
                # the hedge's own counters carry them
            if live:
                by_bucket = self.stats["flushes_by_bucket"]
                by_bucket[bucket] = by_bucket.get(bucket, 0) + 1
                self.stats["evaluated"] += len(live)
                self.stats["occupancy_sum"] += len(live) / bucket
        self.metrics.serving_flush_total.inc({"reason": reason})
        if not live:
            return
        self.metrics.serving_batch_size.observe(len(live))
        self.metrics.serving_batch_occupancy.observe(len(live) / bucket)
        padded = [req.payload for req in live] + [None] * (bucket - len(live))
        # pin the compiled policy-set version for this WHOLE flush
        # before evaluation: every request drained into this batch
        # evaluates against exactly this version, even if a hot swap
        # promotes a newer one while the batch is on the device
        pin = None
        if self._version_provider is not None:
            try:
                pin = self._version_provider()
            except BaseException:
                pin = None  # evaluator owns the unavailability ladder
        pin_rev = getattr(pin, "revision", None)
        if pin_rev is not None:
            with self._stats_lock:
                self.stats["last_flush_revision"] = pin_rev
        # a hedged scalar dispatch racing this batch must evaluate at
        # the SAME pinned revision, or the race could legitimately
        # produce different rows under policy churn
        for req in live:
            req.pin = pin
        t_eval0 = time.monotonic()
        set_dispatch_path(PATH_DEVICE)  # evaluator overwrites on fallback
        try:
            # chaos hook: an armed serving.flush fault lands here, so
            # an injected flush failure takes the SAME path a real
            # evaluator error takes — every waiter gets the exception
            # and the webhook layer resolves it per failurePolicy
            from ..resilience.faults import SITE_SERVING_FLUSH, global_faults

            global_faults.fire(SITE_SERVING_FLUSH)
            results = (self._fn(padded) if self._version_provider is None
                       else self._fn(padded, pin))
            if len(results) < len(live):
                raise RuntimeError("batch evaluator returned wrong arity")
        except BaseException as e:  # propagate to every waiter
            t_eval1 = time.monotonic()
            self.metrics.serving_flusher_seconds.inc(
                {"state": "evaluate"}, t_eval1 - t_eval0)
            # a request a hedged scalar dispatch already resolved keeps
            # its (correct) verdict: the evaluator error only reaches
            # waiters the hedge did not rescue
            wins = [req.resolve(e) for req in live]
            self._record_flush_spans(live, reason, bucket, now, t_eval0,
                                     t_eval1, error=f"{type(e).__name__}: {e}",
                                     revision=pin_rev)
            for req, won in zip(live, wins):
                # claim-gated like the success loop: a completed hedge
                # race owns (and already wrote) this request's record
                if not req.claim_flight():
                    continue
                self._record_flight(
                    req.payload, e if won else req.result,
                    "batched" if won else "hedged_scalar",
                    t_eval1 - req.enqueued_at,
                    req.trace_ctx.trace_id if req.trace_ctx else "",
                    {"eval_s": t_eval1 - t_eval0})
            return
        t_eval1 = time.monotonic()
        self.metrics.serving_flusher_seconds.inc(
            {"state": "evaluate"}, t_eval1 - t_eval0)
        t_resolve0 = time.monotonic()
        # first-writer-wins: a request whose hedged scalar dispatch
        # landed first keeps the scalar rows (bit-identical by the
        # hedge contract) and this flush's result for it is discarded
        wins = [req.resolve(result) for req, result in zip(live, results)]
        t_resolve1 = time.monotonic()
        self.metrics.serving_flusher_seconds.inc(
            {"state": "resolve"}, t_resolve1 - t_resolve0)
        # span recording (and any exporter I/O it triggers) happens
        # AFTER every waiter is woken: the spans carry explicit
        # timestamps, so ordering costs nothing — doing it first would
        # tax every request's latency with tracing overhead
        self._record_flush_spans(live, reason, bucket, now, t_eval0, t_eval1,
                                 revision=pin_rev)
        for req in live:
            if req.trace_ctx is not None:
                global_tracer.record_span(
                    "admission.verdict_dispatch", t_resolve0, t_resolve1,
                    parent=req.trace_ctx, batch_size=len(live))
        if self._flight is not None:
            # AFTER the waiters are resolved and the spans recorded:
            # the flusher thread still holds the dispatch-path thread-
            # local, so the hook can classify device vs fallback. A
            # lost hedge race records here too — path "hedged_scalar",
            # the rows that actually served (this flush's bit-identical
            # copy was discarded)
            eval_s = t_eval1 - t_eval0
            for req, result, won in zip(live, results, wins):
                if not req.claim_flight():
                    # a hedge race claimed this request's record up
                    # front and writes it itself labeled with the
                    # winner (hedged_scalar / hedged_device) — a second
                    # "batched" record here would double-count the
                    # request in the ring and the shadow verifier's
                    # denominators
                    continue
                self._record_flight(
                    req.payload, result if won else req.result,
                    "batched" if won else "hedged_scalar",
                    t_resolve1 - req.enqueued_at,
                    req.trace_ctx.trace_id if req.trace_ctx else "",
                    {"queue_wait_s": max(0.0, (req.drained_at or now)
                                         - req.enqueued_at),
                     "eval_s": eval_s})

    def _record_flush_spans(self, live: List[QueuedRequest], reason: str,
                            bucket: int, drained_at: float,
                            t_eval0: float, t_eval1: float,
                            error: Optional[str] = None,
                            revision: Optional[int] = None) -> None:
        """Per-request flush + dispatch spans: the batch evaluation is
        shared work, but each request's trace must tell the whole story,
        so the shared timings are recorded once per participating trace
        — named by HOW the batch actually resolved (the engine marks the
        device-vs-scalar path in a thread-local this flusher thread
        reads back). With ``error`` set (the evaluator raised), the
        flush span records the failure and no dispatch span is emitted —
        nothing dispatched."""
        traced = [r for r in live if r.trace_ctx is not None]
        if not traced:
            return
        rev_attr = {} if revision is None else {"policyset_revision": revision}
        if error is not None:
            for req in traced:
                global_tracer.record_span(
                    "admission.flush", req.drained_at or drained_at, t_eval1,
                    parent=req.trace_ctx, status="error", reason=reason,
                    batch_size=len(live), bucket=bucket, error=error,
                    **rev_attr)
            return
        path = last_dispatch_path()
        dispatch_name = ("admission.device_dispatch" if path == PATH_DEVICE
                         else "admission.scalar_fallback")
        try:
            from ..resilience.breaker import tpu_breaker

            breaker_state = tpu_breaker().state
        except Exception:
            breaker_state = "unknown"
        for req in traced:
            global_tracer.record_span(
                "admission.flush", req.drained_at or drained_at, t_eval1,
                parent=req.trace_ctx, reason=reason, batch_size=len(live),
                bucket=bucket, **rev_attr)
            global_tracer.record_span(
                dispatch_name, t_eval0, t_eval1, parent=req.trace_ctx,
                engine=path, breaker=breaker_state, batch_size=len(live))

    # -- introspection

    def mean_batch_size(self) -> float:
        with self._stats_lock:
            flushes = sum(self.stats["flushes_by_bucket"].values())
            return self.stats["evaluated"] / flushes if flushes else 0.0

    def state(self) -> Dict[str, Any]:
        """JSON-ready snapshot for /debug/state: queue pressure by
        class, bucket occupancy, flush/shed/hedge accounting."""
        with self._stats_lock:
            stats = {}
            for k, v in self.stats.items():
                if k == "by_class":
                    stats[k] = {pri: dict(c) for pri, c in v.items()}
                else:
                    stats[k] = dict(v) if isinstance(v, dict) else v
        flushes = sum(stats["flushes_by_bucket"].values())
        return {
            "queue_depth": self.queue.depth(),
            "queue_depth_by_class": self.queue.depth_by_class(),
            "high_water": self.queue.high_water,
            "stopped": self._stopped,
            "mean_batch_size": round(
                stats["evaluated"] / flushes, 3) if flushes else 0.0,
            "mean_occupancy": round(
                stats["occupancy_sum"] / flushes, 3) if flushes else 0.0,
            "config": {
                "max_batch_size": self.config.max_batch_size,
                "max_wait_ms": self.config.max_wait_ms,
                "deadline_ms": self.config.deadline_ms,
                "min_bucket": self.config.min_bucket,
                "shed_mode": self.config.shed_mode,
                "class_weights": dict(self.config.class_weights),
                "bulk_max_wait_ms": self.config.bulk_max_wait_ms,
                "hedge_threshold": self.config.hedge_threshold,
                "shed_burn_bulk": self.config.shed_burn_bulk,
                "shed_burn_default": self.config.shed_burn_default,
                "bulk_share": self.config.bulk_share,
                "critical_reserve": self.config.critical_reserve,
                "bulk_shed_mode": self.config.bulk_shed_mode,
            },
            "stats": stats,
        }
