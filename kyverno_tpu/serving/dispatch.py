"""Verdict dispatch — one ScanResult column to per-request rows.

Both consumers of a batch verdict table (the admission pipeline fanning
results back to waiting callers, and the background scanner writing
report rows) must read a resource's verdicts in the SAME compiled-rule
row order, or scan and serve drift apart on multi-rule policies.
"""

from __future__ import annotations

from typing import List, Tuple


def resource_verdicts(result, ci: int) -> List[Tuple[Tuple[str, str], int]]:
    """[( (policy_name, rule_name), code ), ...] for resource column
    `ci`, in compiled-rule row order. `result` is any object with the
    ScanResult shape (`.rules` list + `.verdicts` (rules, N) table)."""
    verdicts = result.verdicts
    return [(rule, int(verdicts[row, ci]))
            for row, rule in enumerate(result.rules)]
