"""Bounded admission queue — classes, fairness, deadlines, backpressure.

Every entry carries its arrival time, an absolute deadline, and a
scheduling class (serving/scheduler.py). The queue refuses work past a
high-water mark (QueueFullError) instead of blocking unboundedly, so
overload surfaces as an explicit shed decision at the pipeline layer
rather than as threads piling up on a lock — and the refusal is
class-aware: the bulk tier is capped at its queue share, and the top
``critical_reserve`` fraction of the queue only admits critical-tier
requests, so a kubelet storm can never occupy the headroom a user
apply needs.

Scheduling happens at DRAIN time over one arrival-ordered store:

- each entry gets a weighted-fair **virtual finish tag** at put()
  (classic WFQ: ``F = max(V, F_last[class]) + 1/weight``), so flushes
  interleave backlogged classes by weight instead of FIFO;
- **urgent** entries (remaining deadline below the urgent window) ride
  the next flush regardless of class credit;
- **bulk** entries coalesce: they are held back until their own
  (longer) timer matures or they can fill a whole batch — except as
  free riders topping a flush up to its padded shape bucket, where the
  device work is already paid for.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .scheduler import priority_of, priority_rank, class_weight


class QueueFullError(RuntimeError):
    """Queue depth crossed a shed threshold; request was shed.
    ``reason`` says which rung refused it: ``high_water`` (global),
    ``critical_reserve`` (non-critical in the reserved headroom), or
    ``class_share`` (bulk past its queue share)."""

    def __init__(self, message: str, reason: str = "high_water"):
        super().__init__(message)
        self.reason = reason


class DeadlineExceededError(TimeoutError):
    """Request spent its whole deadline budget waiting in the queue."""


# sentinel for QueuedRequest.pin before the flush assigns its pinned
# version: drain() marks a request dispatched BEFORE _process acquires
# the pin, and a hedge racing inside that window must be able to tell
# "not assigned yet" from "pinned None (pure-scalar ladder)"
PIN_PENDING = object()


class QueuedRequest:
    __slots__ = ("payload", "enqueued_at", "deadline", "event", "result",
                 "dispatched", "trace_ctx", "drained_at", "cls", "vft",
                 "pin", "hedged", "winner", "flight_claimed", "_rlock")

    def __init__(self, payload: Any, enqueued_at: float, deadline: float,
                 trace_ctx: Any = None, cls: Any = None):
        self.payload = payload
        self.enqueued_at = enqueued_at
        self.deadline = deadline  # absolute monotonic time
        self.event = threading.Event()
        self.result: Any = None
        # the submitting request's SpanContext, carried by VALUE across
        # the queue handoff so the flusher thread's queue-wait / flush /
        # dispatch / verdict spans land in the SAME trace as the
        # submit span (observability/tracing.py)
        self.trace_ctx = trace_ctx
        self.drained_at: float = 0.0
        # set under the queue cv the instant drain() hands this entry
        # to the flusher: submit() only extends its wait past the
        # deadline budget for requests the flusher owns (eval grace),
        # never for ones still stuck in a wedged queue — and because
        # the flag flips atomically with the pop, a waiter's timeout
        # can never observe "queued" for an entry already in a flush
        self.dispatched = False
        # scheduling class (serving/scheduler.py RequestClass) + the
        # weighted-fair virtual finish tag assigned at put()
        self.cls = cls
        self.vft: float = 0.0
        # the compiled policy-set version the flush that drained this
        # entry pinned — the hedged scalar dispatch evaluates at the
        # SAME revision the racing device batch runs, so the race can
        # only ever produce bit-identical rows. PIN_PENDING until the
        # flush assigns it (possibly to None: the pure-scalar ladder)
        self.pin: Any = PIN_PENDING
        # hedge-race state: resolve() is first-writer-wins under a
        # per-request lock so the device batch and a hedged scalar
        # dispatch can race without double resolution; the loser's
        # result is discarded and `winner` names the path that landed
        self.hedged = False
        self.winner: Optional[str] = None
        # one-shot flight-record ownership: the flush's record loops
        # and a racing hedge both want to write THE record for this
        # request — claim_flight() arbitrates so exactly one side does,
        # whatever order they finish in
        self.flight_claimed = False  # guarded-by: _rlock
        self._rlock = threading.Lock()

    def claim_flight(self) -> bool:
        """Atomically claim the right to write this request's flight
        record; False when another path already owns it."""
        with self._rlock:
            if self.flight_claimed:
                return False
            self.flight_claimed = True
            return True

    def release_flight(self) -> None:
        """Hand the record back (a hedge that claimed upfront but then
        errored before producing anything to record)."""
        with self._rlock:
            self.flight_claimed = False

    def resolve(self, result: Any, winner: Optional[str] = None) -> bool:
        """First resolution wins; returns False when the request was
        already resolved (the caller lost the hedge race and must
        discard its result)."""
        with self._rlock:
            if self.event.is_set():
                return False
            self.result = result
            self.winner = winner
            self.event.set()
            return True


class AdmissionQueue:
    """Class-aware request queue guarded by one condition variable:
    put() notifies the flusher; the flusher sleeps on the cv until work
    arrives or a flush timer matures. Without a scheduling ``config``
    the queue degrades to the classic single-FIFO behavior."""

    def __init__(self, high_water: int = 1024, config: Any = None):
        self.high_water = high_water
        self.cv = threading.Condition()
        # set under cv together with the pipeline's stop flag: a put
        # racing shutdown either fails fast here or lands before the
        # final drain — never stranded until the wait timeout
        self.closed = False          # guarded-by: cv
        self._items: List[QueuedRequest] = []  # guarded-by: cv
        self._config = config
        # WFQ state: global virtual time + per-class last finish tag
        self._vt = 0.0               # guarded-by: cv
        self._finish: Dict[Any, float] = {}    # guarded-by: cv
        self._class_depth: Dict[str, int] = {}  # guarded-by: cv
        # wake_times() aggregates (oldest non-bulk arrival, oldest bulk
        # arrival, tightest deadline), maintained incrementally: put()
        # updates them in O(1) — an append at the tail can only SET an
        # empty oldest or tighten the min deadline — and drain() marks
        # them dirty for one O(n) recompute at the next read. Without
        # this, every put's notify_all would send the flusher on an
        # O(depth) walk under the cv submitters contend on.
        self._agg: Optional[tuple] = (None, None, None)  # guarded-by: cv
        # drain() telemetry for the pipeline (single flusher reader)
        self.last_drain_info: Dict[str, Any] = {}

    # -- write side

    def put(self, payload: Any, deadline: float,
            now: Optional[float] = None, trace_ctx: Any = None,
            cls: Any = None) -> QueuedRequest:
        req = QueuedRequest(payload, now if now is not None
                            else time.monotonic(), deadline, trace_ctx,
                            cls=cls)
        pri = priority_of(cls)
        cfg = self._config
        with self.cv:
            if self.closed:
                raise RuntimeError("admission queue is closed")
            depth = len(self._items)
            if depth >= self.high_water:
                raise QueueFullError(
                    f"admission queue at high-water mark "
                    f"({self.high_water})", reason="high_water")
            if cfg is not None:
                reserve = float(getattr(cfg, "critical_reserve", 0.0) or 0.0)
                if pri != "critical" and reserve > 0:
                    cap = max(1, int(self.high_water * (1.0 - reserve)))
                    if depth >= cap:
                        raise QueueFullError(
                            f"queue headroom reserved for critical class "
                            f"(depth {depth} >= {cap})",
                            reason="critical_reserve")
                share = float(getattr(cfg, "bulk_share", 1.0))
                if pri == "bulk" and share < 1.0:
                    bcap = max(1, int(self.high_water * share))
                    if self._class_depth.get("bulk", 0) >= bcap:
                        raise QueueFullError(
                            f"bulk class at its queue share ({bcap})",
                            reason="class_share")
            # weighted-fair finish tag: flows (class keys) interleave
            # by weight when backlogged; an idle flow re-enters at the
            # current virtual time instead of collecting credit
            key = cls if cls is not None else pri
            w = class_weight(getattr(cfg, "class_weights", None), cls)
            req.vft = max(self._vt, self._finish.get(key, 0.0)) + 1.0 / w
            self._finish[key] = req.vft
            self._items.append(req)
            self._class_depth[pri] = self._class_depth.get(pri, 0) + 1
            if self._agg is not None:
                nb, b, dl = self._agg
                if pri == "bulk":
                    b = req.enqueued_at if b is None else b
                else:
                    nb = req.enqueued_at if nb is None else nb
                dl = deadline if dl is None else min(dl, deadline)
                self._agg = (nb, b, dl)
            self.cv.notify_all()
        return req

    # -- flusher side (callers hold self.cv unless noted)

    def wake_times(self, config: Any) -> Dict[str, float]:
        """Absolute times at which a flush trigger matures: ``timer``
        (oldest non-bulk entry + max_wait), ``bulk_timer`` (oldest bulk
        entry + bulk_max_wait — the coalescing window), ``deadline``
        (tightest entry deadline - lead). Empty when the queue is."""
        if not self._items:
            return {}
        max_wait = config.max_wait_ms / 1000.0
        bulk_wait = getattr(config, "bulk_max_wait_ms", None)
        bulk_wait = max_wait if bulk_wait is None else bulk_wait / 1000.0
        lead = config.deadline_lead_ms / 1000.0
        if self._agg is None:  # dirtied by a drain: one O(n) recompute
            oldest_nb = oldest_b = None
            dmin = None
            for r in self._items:
                if priority_of(r.cls) == "bulk":
                    if oldest_b is None or r.enqueued_at < oldest_b:
                        oldest_b = r.enqueued_at
                else:
                    if oldest_nb is None or r.enqueued_at < oldest_nb:
                        oldest_nb = r.enqueued_at
                if dmin is None or r.deadline < dmin:
                    dmin = r.deadline
            self._agg = (oldest_nb, oldest_b, dmin)
        oldest_nb, oldest_b, dmin = self._agg
        out: Dict[str, float] = {}
        if oldest_nb is not None:
            out["timer"] = oldest_nb + max_wait
        if oldest_b is not None:
            out["bulk_timer"] = oldest_b + bulk_wait
        if dmin is not None:
            out["deadline"] = dmin - lead
        return out

    def drain(self, max_n: int, now: Optional[float] = None,
              config: Any = None, stopping: bool = False
              ) -> List[QueuedRequest]:
        """Pop up to max_n entries in scheduler order (legacy FIFO
        when no config). Callers hold self.cv."""
        now = time.monotonic() if now is None else now
        self._agg = None  # wake_times() recomputes after any pop
        if config is None:
            batch, self._items = self._items[:max_n], self._items[max_n:]
            self.last_drain_info = {}
        else:
            batch = self._select_locked(max_n, now, config, stopping)
        t = time.monotonic()
        for req in batch:
            req.dispatched = True
            req.drained_at = t  # queue-wait span boundary
            pri = priority_of(req.cls)
            if self._class_depth.get(pri, 0) > 0:
                self._class_depth[pri] -= 1
        if batch:
            self._vt = max([self._vt] + [r.vft for r in batch])
            # prune idle flows: a finish tag at or below the virtual
            # time is indistinguishable from no entry (the flow would
            # re-enter at V either way), and flow keys carry request
            # namespaces — without pruning, namespace churn grows
            # _finish without bound on a never-quiescent server
            if len(self._finish) > 64:
                vt = self._vt
                self._finish = {k: f for k, f in self._finish.items()
                                if f > vt}
        if not self._items:
            # quiescent queue: reset the virtual clock so tags do not
            # grow without bound across a long-lived process
            self._vt = 0.0
            self._finish.clear()
        return batch

    def _select_locked(self, max_n: int, now: float, cfg: Any,
                stopping: bool) -> List[QueuedRequest]:
        items = self._items
        if stopping:
            # shutdown flush: everything drains, latency-critical
            # waiters first so they resolve before bulk
            order = sorted(items, key=lambda r: (priority_rank(r.cls),
                                                 r.enqueued_at))
            chosen = order[:max_n]
            self.last_drain_info = {"stopping": True}
        else:
            # 1) urgent: remaining deadline inside the urgent window
            #    rides the next flush regardless of class credit (the
            #    window never undercuts the deadline-flush lead, or a
            #    deadline-triggered flush could strand its own trigger)
            urgent_s = max(getattr(cfg, "urgent_ms", 0.0),
                           cfg.deadline_lead_ms) / 1000.0
            urgent = sorted((r for r in items
                             if r.deadline - now <= urgent_s),
                            key=lambda r: r.deadline)
            chosen = urgent[:max_n]
            chosen_ids = {id(r) for r in chosen}
            # 2) weighted-fair order across the non-bulk classes
            nonbulk = sorted((r for r in items
                              if id(r) not in chosen_ids
                              and priority_of(r.cls) != "bulk"),
                             key=lambda r: r.vft)
            for r in nonbulk:
                if len(chosen) >= max_n:
                    break
                chosen.append(r)
                chosen_ids.add(id(r))
            # 3) bulk coalesces: eligible only when its own timer
            #    matured or it can fill a whole batch — otherwise it
            #    only tops the flush up to the padded shape bucket
            #    (free riders on slots that would have been padding)
            bulk = sorted((r for r in items
                           if id(r) not in chosen_ids
                           and priority_of(r.cls) == "bulk"),
                          key=lambda r: r.vft)
            topup = 0
            mature = False
            if bulk:
                bulk_wait_s = getattr(cfg, "bulk_max_wait_ms",
                                      cfg.max_wait_ms) / 1000.0
                oldest = min(r.enqueued_at for r in bulk)
                mature = (len(bulk) >= max_n
                          or now - oldest >= bulk_wait_s)
                if mature:
                    for r in bulk:
                        if len(chosen) >= max_n:
                            break
                        chosen.append(r)
                        chosen_ids.add(id(r))
                elif chosen:
                    cap = min(cfg.bucket(len(chosen)), max_n)
                    for r in bulk:
                        if len(chosen) >= cap:
                            break
                        chosen.append(r)
                        chosen_ids.add(id(r))
                        topup += 1
            self.last_drain_info = {
                "urgent": min(len(urgent), max_n),
                "bulk_topup": topup,
                "bulk_mature": mature,
            }
        chosen_ids = {id(r) for r in chosen}
        self._items = [r for r in items if id(r) not in chosen_ids]
        return chosen

    def drain_all(self) -> List[QueuedRequest]:
        """Pop everything, priority tiers first (shutdown path: every
        waiter must resolve, latency-critical ones before bulk)."""
        with self.cv:
            batch, self._items = self._items, []
            self._class_depth.clear()
            self._vt = 0.0
            self._finish.clear()
            self._agg = (None, None, None)
        return sorted(batch, key=lambda r: (priority_rank(r.cls),
                                            r.enqueued_at))

    # -- introspection

    def depth(self) -> int:
        return len(self._items)

    def depth_by_class(self) -> Dict[str, int]:
        # lock-free snapshot: _class_depth is written only under the cv,
        # but this runs on every submit for a GAUGE — taking the cv here
        # would serialize submitters against the flusher for telemetry.
        # The keys are the three fixed tiers, so the dict stops resizing
        # after warmup; the locked path covers the rare early race.
        try:
            return {k: v for k, v in list(self._class_depth.items())
                    if v > 0}
        except RuntimeError:
            with self.cv:
                return {k: v for k, v in self._class_depth.items() if v > 0}

    def oldest(self) -> Optional[QueuedRequest]:
        return self._items[0] if self._items else None
