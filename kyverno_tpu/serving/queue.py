"""Bounded admission queue — arrival times, deadlines, backpressure.

Every entry carries its arrival time and an absolute deadline; the
queue refuses work past a high-water mark (QueueFullError) instead of
blocking unboundedly, so overload surfaces as an explicit shed decision
at the pipeline layer rather than as threads piling up on a lock.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional


class QueueFullError(RuntimeError):
    """Queue depth crossed the high-water mark; request was shed."""


class DeadlineExceededError(TimeoutError):
    """Request spent its whole deadline budget waiting in the queue."""


class QueuedRequest:
    __slots__ = ("payload", "enqueued_at", "deadline", "event", "result",
                 "dispatched", "trace_ctx", "drained_at")

    def __init__(self, payload: Any, enqueued_at: float, deadline: float,
                 trace_ctx: Any = None):
        self.payload = payload
        self.enqueued_at = enqueued_at
        self.deadline = deadline  # absolute monotonic time
        self.event = threading.Event()
        self.result: Any = None
        # the submitting request's SpanContext, carried by VALUE across
        # the queue handoff so the flusher thread's queue-wait / flush /
        # dispatch / verdict spans land in the SAME trace as the
        # submit span (observability/tracing.py)
        self.trace_ctx = trace_ctx
        self.drained_at: float = 0.0
        # set under the queue cv the instant drain() hands this entry
        # to the flusher: submit() only extends its wait past the
        # deadline budget for requests the flusher owns (eval grace),
        # never for ones still stuck in a wedged queue — and because
        # the flag flips atomically with the pop, a waiter's timeout
        # can never observe "queued" for an entry already in a flush
        self.dispatched = False

    def resolve(self, result: Any) -> None:
        self.result = result
        self.event.set()


class AdmissionQueue:
    """FIFO of QueuedRequests guarded by one condition variable: put()
    notifies the flusher; the flusher sleeps on the cv until work
    arrives or its flush timer matures."""

    def __init__(self, high_water: int = 1024):
        self.high_water = high_water
        self.cv = threading.Condition()
        # set under cv together with the pipeline's stop flag: a put
        # racing shutdown either fails fast here or lands before the
        # final drain — never stranded until the wait timeout
        self.closed = False
        self._items: List[QueuedRequest] = []

    def put(self, payload: Any, deadline: float,
            now: Optional[float] = None, trace_ctx: Any = None) -> QueuedRequest:
        req = QueuedRequest(payload, now if now is not None
                            else time.monotonic(), deadline, trace_ctx)
        with self.cv:
            if self.closed:
                raise RuntimeError("admission queue is closed")
            if len(self._items) >= self.high_water:
                raise QueueFullError(
                    f"admission queue at high-water mark ({self.high_water})")
            self._items.append(req)
            self.cv.notify_all()
        return req

    def drain(self, max_n: int) -> List[QueuedRequest]:
        """Pop up to max_n oldest entries. Callers hold self.cv."""
        batch, self._items = self._items[:max_n], self._items[max_n:]
        now = time.monotonic()
        for req in batch:
            req.dispatched = True
            req.drained_at = now  # queue-wait span boundary
        return batch

    def drain_all(self) -> List[QueuedRequest]:
        """Pop everything (shutdown path: every waiter must resolve)."""
        with self.cv:
            batch, self._items = self._items, []
        return batch

    def depth(self) -> int:
        return len(self._items)

    def oldest(self) -> Optional[QueuedRequest]:
        return self._items[0] if self._items else None
