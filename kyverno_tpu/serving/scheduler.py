"""Request classes for admission scheduling — tenants, tiers, shedding.

Production admission traffic is not uniform: kubelet-storm rescans and
CI bursts share the queue with latency-critical user applies. This
module defines the *class model* the serving pipeline schedules by:

- **RequestClass** — the flow identity ``(tenant, operation,
  priority)``. Each distinct class is its own weighted-fair flow in the
  queue; the priority *tier* (``critical`` / ``default`` / ``bulk``)
  decides its weight, its shed thresholds, and its flush eligibility.
- **classify_request** — class extraction from admission-request
  metadata (username globs, dry-run flag, groups, a resource
  annotation), driven by a **ClassifyConfig** the ``serve`` flags tune.
- **burn-driven shed ladder helpers** — the bulk tier sheds first when
  the SLO burn signal (observability/analytics.py SloTracker) crosses
  its threshold; the default tier sheds at a higher threshold;
  the critical tier is never burn-shed (only the global high-water
  mark can refuse it).

Everything here is stdlib-only and jax-free, like the rest of
``serving/`` — the scheduler must be importable by the CLI and the
metrics layer without pulling in the device runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

# priority tiers, most-protected first. Rank drives shutdown-drain
# order and the shed ladder; WEIGHTS drives steady-state fairness.
PRIORITY_CRITICAL = "critical"
PRIORITY_DEFAULT = "default"
PRIORITY_BULK = "bulk"
PRIORITIES = (PRIORITY_CRITICAL, PRIORITY_DEFAULT, PRIORITY_BULK)
PRIORITY_RANK = {PRIORITY_CRITICAL: 0, PRIORITY_DEFAULT: 1, PRIORITY_BULK: 2}

DEFAULT_CLASS_WEIGHTS = {PRIORITY_CRITICAL: 8.0, PRIORITY_DEFAULT: 4.0,
                         PRIORITY_BULK: 1.0}

# resource annotation that routes a single request's class. The
# annotation lives on the ADMITTED RESOURCE — requester-controlled —
# so by default it may only DEMOTE (bulk/default): honoring a
# self-stamped "critical" would let exactly the flood traffic this
# scheduler exists to contain promote itself past the shed ladder.
# Promotion via the annotation requires the operator to opt in
# (ClassifyConfig.trust_annotation_critical / identity-based
# --critical-users globs stay the trusted promotion path).
PRIORITY_ANNOTATION = "policies.kyverno.io/priority"


class RequestClass(NamedTuple):
    """One weighted-fair flow: tenant x operation x priority tier."""

    tenant: str
    operation: str
    priority: str


def priority_of(cls: Any) -> str:
    """Priority tier of a class descriptor; ``None`` (legacy callers
    that never classify) and bare strings degrade gracefully."""
    if cls is None:
        return PRIORITY_DEFAULT
    pri = getattr(cls, "priority", cls)
    return pri if pri in PRIORITY_RANK else PRIORITY_DEFAULT


def priority_rank(cls: Any) -> int:
    return PRIORITY_RANK[priority_of(cls)]


@dataclass
class ClassifyConfig:
    """Class-extraction rules (``serve --bulk-users/--critical-users``).

    Username patterns are shell globs matched case-sensitively against
    ``request.userInfo.username``. Defaults mark the classic storm
    sources — kubelets and kube-system controllers — as bulk; dry-run
    admissions (rescan storms replay with dryRun) are bulk too."""

    bulk_users: Tuple[str, ...] = ("system:node:*",
                                   "system:serviceaccount:kube-system:*")
    critical_users: Tuple[str, ...] = ()
    bulk_groups: Tuple[str, ...] = ("system:nodes",)
    dry_run_bulk: bool = True
    annotation: str = PRIORITY_ANNOTATION
    # opt-in: honor a requester-stamped "critical" annotation. OFF by
    # default — the annotation is on the admitted resource, so trusting
    # it lets any flood self-promote past the overload ladder
    trust_annotation_critical: bool = False


def _match_any(patterns: Sequence[str], value: str) -> bool:
    return any(fnmatchcase(value, p) for p in patterns if p)


def classify_request(config: Optional[ClassifyConfig] = None, *,
                     operation: str = "", username: str = "",
                     namespace: str = "", groups: Sequence[str] = (),
                     dry_run: bool = False,
                     resource: Optional[Dict[str, Any]] = None
                     ) -> RequestClass:
    """Extract the scheduling class from admission-request metadata.

    Precedence: trusted identity first — critical user globs, then
    dry-run / bulk user / bulk group demotion. The resource annotation
    may only DEMOTE from there, and never below what the operator's
    identity globs granted: a ``--critical-users`` identity stays
    critical regardless of the annotation, because the annotation lives
    on the admitted OBJECT — authored by whoever last wrote it, not by
    the requester — so honoring it against a trusted identity would let
    anyone who can annotate an object demote someone else's critical
    traffic into the shed ladder. It PROMOTES to critical only when the
    operator opted in via ``trust_annotation_critical``. The tenant is
    the namespace (cluster-scoped resources fall back to the username,
    then ``_cluster``) so per-tenant fairness holds inside a tier."""
    cfg = config or ClassifyConfig()
    tenant = namespace or username or "_cluster"
    if _match_any(cfg.critical_users, username):
        pri = PRIORITY_CRITICAL
    elif (dry_run and cfg.dry_run_bulk) \
            or _match_any(cfg.bulk_users, username) \
            or any(g in cfg.bulk_groups for g in groups or ()):
        pri = PRIORITY_BULK
    else:
        pri = PRIORITY_DEFAULT
    annotated = ""
    if resource is not None:
        meta = resource.get("metadata") or {}
        annotated = str((meta.get("annotations") or {}
                         ).get(cfg.annotation, "")).lower()
    if annotated in PRIORITY_RANK and annotated != pri:
        if PRIORITY_RANK[annotated] > PRIORITY_RANK[pri]:
            if pri != PRIORITY_CRITICAL:
                pri = annotated  # demotion, but never of trusted identity
        elif cfg.trust_annotation_critical:
            pri = annotated  # promotion: operator opt-in only
    return RequestClass(tenant=tenant, operation=operation, priority=pri)


def class_weight(weights: Optional[Dict[str, float]], cls: Any) -> float:
    w = float((weights or DEFAULT_CLASS_WEIGHTS).get(
        priority_of(cls), DEFAULT_CLASS_WEIGHTS[PRIORITY_DEFAULT]))
    if not (0.0 < w < float("inf")):
        # NaN/inf/non-positive from a library-built dict would poison
        # every finish tag (parse_class_weights rejects them at the CLI)
        w = DEFAULT_CLASS_WEIGHTS[PRIORITY_DEFAULT]
    return max(w, 1e-9)


def parse_class_weights(text: str) -> Dict[str, float]:
    """``critical=8,default=4,bulk=1`` -> weight dict (serve flag)."""
    out = dict(DEFAULT_CLASS_WEIGHTS)
    for pair in (text or "").split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(f"bad class weight {pair!r} (want tier=weight)")
        tier, _, raw = pair.partition("=")
        tier = tier.strip()
        if tier not in PRIORITY_RANK:
            raise ValueError(
                f"unknown priority tier {tier!r} (known: {PRIORITIES})")
        w = float(raw)
        # `not (w > 0)` also rejects NaN, which passes a `w <= 0`
        # check and would silently poison every WFQ finish tag
        if not (w > 0) or w == float("inf"):
            raise ValueError(
                f"class weight must be positive and finite: {pair!r}")
        out[tier] = w
    return out


def burn_shed_threshold(config: Any, cls: Any) -> float:
    """The burn-rate level above which this class sheds; 0 disables.
    The ladder: bulk first (lowest threshold), then default; critical
    never burn-sheds — only the hard high-water mark refuses it."""
    pri = priority_of(cls)
    if pri == PRIORITY_BULK:
        return float(getattr(config, "shed_burn_bulk", 0.0) or 0.0)
    if pri == PRIORITY_DEFAULT:
        return float(getattr(config, "shed_burn_default", 0.0) or 0.0)
    return 0.0
