"""TPU device plane: policy compilation + batched evaluation.

The scalar engine (kyverno_tpu.engine) is the semantic oracle; this
package compiles a policy set into a trace-time-specialized JAX
program that evaluates the policy x resource cross-product as one
batched device computation:

- flatten:   resource JSON -> padded row tables (path hashes, typed
             value lanes pre-parsed on host, byte pool for globs)
- metadata:  match/exclude features (GVK, name/ns bytes, label hashes)
- ir:        Rule -> device IR with capability analysis; rules using
             constructs outside the device subset fall back to the
             scalar engine per rule (never wrong, only slower)
- evaluator: IR -> jitted batch program, vmapped over resources and
             unrolled over rules; MXU-friendly instance joins
- engine:    TpuEngine facade + sharded scan entry points
- cache:     content-addressed verdict/encode LRUs + the persistent
             XLA compile cache (the amortization levers)
- pipeline:  double-buffered scan — encode k+1, device k, and host
             completion k-1 overlap instead of serializing
"""

# Lazy exports (PEP 562): the compiler/engine pull in JAX, but the
# encode-pool worker processes (encode/worker.py) import ONLY the host
# side of this package (flatten, metadata, hashing) and must stay
# JAX-free — an eager import here would load the full device runtime
# into every spawned encoder.
_LAZY = {
    "CompiledPolicySet": ".compiler",
    "compile_policy_set": ".compiler",
    "ScanResult": ".engine",
    "TpuEngine": ".engine",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)


def __dir__():
    return sorted(list(globals()) + list(_LAZY))

