"""Content-addressed result caches + the persistent XLA compile cache.

Hardware pattern-matching engines get their throughput by amortizing:
compiled automata are reused across packets, and identical flows skip
re-matching entirely (PAPERS: Hyperflex SIMD-DFA DPI; in-memory
pattern-matching codesign). This module brings the same two levers to
the policy engine:

- **VerdictCache** — a bounded LRU keyed by content, not identity:
  (compiled-policy-set content key, resource content hash, digest of
  ns-labels/operation/userinfo) -> that resource's (num_rules,) verdict
  column. Repeat admissions of identical manifests and full rescans of
  a mostly-unchanged cluster skip encoding AND the device entirely.
  Invalidation is free: a policy mutation, quarantine change, config
  knob, or context-dep (compile-folded configmap) movement changes the
  policy-set key; a resource edit changes the resource hash; an
  ns-label or userinfo change changes the request digest. Nothing is
  ever explicitly flushed — stale keys just stop being looked up and
  age out of the LRU.

- **EncodeRowCache** — resource content hash (+ encode-path config
  key) -> the resource's encoded lane rows, trimmed to the rows it
  actually uses. A verdict-cache miss after a policy-set revision bump
  still skips the Python tree-walk re-encode of unchanged resources
  (the encode key deliberately excludes policy CONTENT — only the
  encode caps and compiled byte paths shape the rows).

- **enable_xla_compile_cache** — turns on JAX's persistent compilation
  cache (``jax_compilation_cache_dir``) so ``device_fn`` builds survive
  process restarts: the lifecycle compile-ahead warm scan and the bench
  probe pay the multi-minute XLA build once per (program, shape), not
  once per process.

Caching is only consulted when the compiled set is *cache eligible*
(TpuEngine.cache_eligible): no runtime dyn-operand slots (those do real
context-backend I/O per request) and no host-routed rule with context
entries (the scalar oracle would do live I/O). Compile-time folded
configmaps are fine — their content hashes ride the policy-set key.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


def digest(*parts: Any) -> str:
    """Stable short digest over JSON-serializable parts."""
    payload = json.dumps(parts, sort_keys=True, default=str,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def resource_content_hash(resource: Any) -> Optional[str]:
    """Content hash of one resource dict; None when the object is not
    canonically hashable (non-JSON values) — such resources simply
    bypass the caches, they are never mis-keyed. MUST stay the same
    function as cluster/snapshot.py resource_hash (asserted in tests):
    the scanner threads the snapshot's stored hashes into
    verdict_cache_keys instead of re-serializing every body."""
    try:
        payload = json.dumps(resource, sort_keys=True,
                             separators=(",", ":"))
    except (TypeError, ValueError):
        return None
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def request_digest(ns_labels: Optional[Dict[str, str]], operation: str,
                   info: Any) -> str:
    """Digest of the per-request evaluation context that is NOT the
    resource body: the resource's namespace labels (namespaceSelector
    results can flip without the resource changing), the admission
    operation (raw — '' and 'CREATE' evaluate differently), and the
    requesting identity."""
    ident: Tuple = ()
    if info is not None:
        ident = (getattr(info, "username", ""), getattr(info, "uid", ""),
                 tuple(getattr(info, "groups", ()) or ()),
                 tuple(getattr(info, "roles", ()) or ()),
                 tuple(getattr(info, "cluster_roles", ()) or ()))
    return digest(sorted((ns_labels or {}).items()), operation or "", ident)


class LruCache:
    """Thread-safe bounded LRU. ``capacity <= 0`` disables the cache
    (get always misses, put is a no-op) — the disable knob the CLI
    flags and tests use."""

    def __init__(self, capacity: int, name: str = "lru"):
        self.name = name
        self._capacity = int(capacity)
        self._data: "OrderedDict[Any, Any]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self.evictions = 0

    @property
    def capacity(self) -> int:
        return self._capacity

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._capacity = int(capacity)
            while len(self._data) > max(self._capacity, 0):
                self._data.popitem(last=False)
                self.evictions += 1

    @property
    def enabled(self) -> bool:
        return self._capacity > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get(self, key: Any) -> Optional[Any]:
        with self._lock:
            if key not in self._data:
                return None
            self._data.move_to_end(key)
            return self._data[key]

    def put(self, key: Any, value: Any) -> None:
        if self._capacity <= 0:
            return
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self._capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._data.clear()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class VerdictCache:
    """LRU of verdict COLUMNS: key -> (num_rules,) int32 array. Values
    are stored and returned as copies so callers can never alias a
    cached column into a mutable verdict table."""

    def __init__(self, capacity: Optional[int] = None, metrics=None):
        if capacity is None:
            capacity = _env_int("KYVERNO_TPU_VERDICT_CACHE", 65536)
        self._lru = LruCache(capacity, name="verdict")
        self._metrics = metrics
        # optional fleet fan-out hook (fleet/manager.py): called with
        # (key, column) AFTER a locally computed column lands, so one
        # replica's scan warms its peers. Set/cleared by the fleet
        # manager; never called for peer-received columns (put with
        # fanout=False) — a column cannot ping-pong across the fleet.
        self.on_put = None

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    @property
    def enabled(self) -> bool:
        return self._lru.enabled

    @property
    def evictions(self) -> int:
        return self._lru.evictions

    def set_capacity(self, capacity: int) -> None:
        self._lru.set_capacity(capacity)

    def __len__(self) -> int:
        return len(self._lru)

    def clear(self) -> None:
        self._lru.clear()

    def get(self, key: Any,
            expect_rows: Optional[int] = None) -> Optional[np.ndarray]:
        """Lookup; with ``expect_rows`` a stored column whose length
        does not match the caller's compiled rule count is a MISS —
        the one place the wrong-shape defense lives (a hostile or
        racing fleet push may land a length-consistent column under a
        content key before the receive-side shape check can know the
        active rule count; no reader may crash on it)."""
        m = self._registry()
        col = self._lru.get(key)
        if col is not None and expect_rows is not None \
                and col.shape[0] != expect_rows:
            col = None
        if col is None:
            m.verdict_cache.inc({"outcome": "miss"})
            return None
        m.verdict_cache.inc({"outcome": "hit"})
        return col.copy()

    def bypass(self) -> None:
        """Count a scan that skipped the cache (ineligible set)."""
        self._registry().verdict_cache.inc({"outcome": "bypass"})

    def hit_rate(self) -> float:
        """Lifetime hit rate (hits / lookups) — the amortization signal
        /debug/utilization and the bench rollup surface."""
        m = self._registry()
        hits = m.verdict_cache.value({"outcome": "hit"})
        misses = m.verdict_cache.value({"outcome": "miss"})
        total = hits + misses
        return round(hits / total, 4) if total else 0.0

    def peek(self, key: Any) -> Optional[np.ndarray]:
        """Lookup WITHOUT hit/miss accounting — the fleet peer-fetch
        server path (a peer probing this cache must not skew the local
        hit-rate signal)."""
        col = self._lru.get(key)
        return col.copy() if col is not None else None

    def put(self, key: Any, column: np.ndarray,
            fanout: bool = True) -> None:
        if not self._lru.enabled:
            return
        before = self._lru.evictions
        self._lru.put(key, np.array(column, dtype=np.int32, copy=True))
        m = self._registry()
        evicted = self._lru.evictions - before
        if evicted:
            m.verdict_cache_evictions.inc(value=evicted)
        m.verdict_cache_size.set(len(self._lru))
        hook = self.on_put
        if fanout and hook is not None:
            try:
                hook(key, column)  # bounded enqueue, never blocks
            except Exception:
                pass


# per-resource row lanes stored trimmed to the rows the resource uses
# (everything past n_rows holds RowBatch defaults); pool slots trimmed
# to the last one carrying bytes
class _EncodedRows:
    __slots__ = ("lanes", "pool", "pool_len", "n_rows", "fallback")

    def __init__(self, lanes, pool, pool_len, n_rows, fallback):
        self.lanes = lanes
        self.pool = pool
        self.pool_len = pool_len
        self.n_rows = n_rows
        self.fallback = fallback


def extract_rows(batch, i: int) -> _EncodedRows:
    """Trim row ``i`` of an encoded RowBatch to the rows/pool slots it
    actually uses — the transferable per-resource form shared by the
    encode-row cache and the encoder-pool workers (encode/tasks.py),
    so pooled results and cached results are the same bytes."""
    m = int(batch.n_rows[i])
    lanes: Dict[str, np.ndarray] = {}
    for name, arr in batch.arrays().items():
        if name in ("pool", "pool_len", "n_rows", "fallback"):
            continue
        lanes[name] = arr[i, :m].copy()
    used = np.nonzero(batch.pool_len[i] > 0)[0]
    s = int(used.max()) + 1 if used.size else 0
    pool = batch.pool[i, :s].copy() if s else None
    pool_len = batch.pool_len[i, :s].copy() if s else None
    return _EncodedRows(lanes, pool, pool_len, m, int(batch.fallback[i]))


def apply_rows(entry: _EncodedRows, batch, i: int) -> None:
    """Write a trimmed per-resource row entry into row ``i`` of a fresh
    RowBatch (whose lanes still hold constructor defaults)."""
    for name, row in entry.lanes.items():
        getattr(batch, name)[i, : row.shape[0]] = row
    if entry.pool is not None:
        s = entry.pool.shape[0]
        batch.pool[i, :s] = entry.pool
        batch.pool_len[i, :s] = entry.pool_len
    batch.n_rows[i] = entry.n_rows
    batch.fallback[i] = entry.fallback


def apply_rows_multi(entries: Sequence[_EncodedRows], batch,
                     idxs: Sequence[int]) -> None:
    """Vectorized twin of ``apply_rows`` for a batch with >= 2 cache
    hits: ONE flat fancy-index scatter per lane across every hit row
    instead of a Python iteration per resource (bit-identical to the
    loop — asserted in tests). The dominant admission-warm case (most
    of a flush restores from the LRU or the columnar store) stops
    paying ~25 numpy scalar stores per resource."""
    if not entries:
        return
    if len(entries) == 1:
        apply_rows(entries[0], batch, idxs[0])
        return
    max_rows = batch.cfg.max_rows
    counts = np.array([e.n_rows for e in entries], dtype=np.int64)
    # flat destination indices: rows 0..m_i of each hit resource
    reps = np.repeat(np.asarray(idxs, dtype=np.int64) * max_rows, counts)
    within = np.concatenate([np.arange(m, dtype=np.int64) for m in counts]) \
        if counts.sum() else np.zeros((0,), dtype=np.int64)
    dst = reps + within
    lane_names = entries[0].lanes.keys()
    for name in lane_names:
        src = np.concatenate([e.lanes[name] for e in entries])
        getattr(batch, name).ravel()[dst] = src
    slots = batch.cfg.byte_pool_slots
    pdst: list = []
    psrc_pool: list = []
    psrc_len: list = []
    for e, i in zip(entries, idxs):
        if e.pool is None:
            continue
        s = e.pool.shape[0]
        pdst.append(i * slots + np.arange(s, dtype=np.int64))
        psrc_pool.append(e.pool)
        psrc_len.append(e.pool_len)
    if pdst:
        flat = np.concatenate(pdst)
        batch.pool.reshape(-1, batch.cfg.byte_pool_width)[flat] = \
            np.concatenate(psrc_pool)
        batch.pool_len.ravel()[flat] = np.concatenate(psrc_len)
    ia = np.asarray(idxs, dtype=np.int64)
    batch.n_rows[ia] = counts
    batch.fallback[ia] = np.array([e.fallback for e in entries],
                                  dtype=np.uint8)


class EncodeRowCache:
    """LRU of per-resource encoded rows. Keys are
    (encode-path key, resource content hash): the encode-path key
    covers the EncodeConfig caps and the compiled byte-path sets —
    everything that shapes the rows — and deliberately NOT the policy
    content, so a policy-set revision bump keeps every entry warm."""

    def __init__(self, capacity: Optional[int] = None, metrics=None):
        if capacity is None:
            capacity = _env_int("KYVERNO_TPU_ENCODE_CACHE", 8192)
        self._lru = LruCache(capacity, name="encode")
        self._metrics = metrics

    def _registry(self):
        if self._metrics is None:
            from ..observability.metrics import global_registry

            self._metrics = global_registry
        return self._metrics

    @property
    def enabled(self) -> bool:
        return self._lru.enabled

    @property
    def evictions(self) -> int:
        return self._lru.evictions

    def set_capacity(self, capacity: int) -> None:
        self._lru.set_capacity(capacity)

    def __len__(self) -> int:
        return len(self._lru)

    def clear(self) -> None:
        self._lru.clear()

    def hit_rate(self) -> float:
        """Lifetime hit rate (hits / lookups)."""
        m = self._registry()
        hits = m.encode_cache.value({"outcome": "hit"})
        misses = m.encode_cache.value({"outcome": "miss"})
        total = hits + misses
        return round(hits / total, 4) if total else 0.0

    @staticmethod
    def encode_key(encode_cfg, byte_paths, key_byte_paths) -> str:
        return digest(
            (encode_cfg.max_rows, encode_cfg.max_instances,
             encode_cfg.byte_pool_slots, encode_cfg.byte_pool_width),
            sorted(byte_paths or ()), sorted(key_byte_paths or ()))

    def get_into(self, key: Any, batch, i: int) -> bool:
        """Write the cached rows for ``key`` into row ``i`` of a fresh
        RowBatch (whose lanes still hold constructor defaults). Returns
        False on miss."""
        entry = self.get_entry(key)
        if entry is None:
            return False
        apply_rows(entry, batch, i)
        return True

    def get_entry(self, key: Any) -> Optional[_EncodedRows]:
        """The trimmed entry itself (hit/miss counted) — callers that
        collect several hits apply them in one vectorized pass via
        ``apply_rows_multi`` instead of a per-resource loop."""
        m = self._registry()
        entry: Optional[_EncodedRows] = self._lru.get(key)
        if entry is None:
            m.encode_cache.inc({"outcome": "miss"})
            return None
        m.encode_cache.inc({"outcome": "hit"})
        return entry

    def put_from(self, key: Any, batch, i: int) -> None:
        """Trim + store row ``i`` of an encoded RowBatch."""
        if not self._lru.enabled:
            return
        self.put_entry(key, extract_rows(batch, i))

    def put_entry(self, key: Any, entry: _EncodedRows) -> None:
        """Store an already-trimmed per-resource entry (the encoder
        pool's rows results arrive in this form — they warm the cache
        without a round-trip through a RowBatch)."""
        if not self._lru.enabled:
            return
        before = self._lru.evictions
        self._lru.put(key, entry)
        reg = self._registry()
        evicted = self._lru.evictions - before
        if evicted:
            reg.encode_cache_evictions.inc(value=evicted)


global_verdict_cache = VerdictCache()
global_encode_cache = EncodeRowCache()


def configure(verdict_capacity: Optional[int] = None,
              encode_capacity: Optional[int] = None) -> None:
    """Resize (0 disables) the process-wide caches — the CLI knobs."""
    if verdict_capacity is not None:
        global_verdict_cache.set_capacity(verdict_capacity)
    if encode_capacity is not None:
        global_encode_cache.set_capacity(encode_capacity)


# ---------------------------------------------------------------------------
# persistent XLA compilation cache

DEFAULT_XLA_CACHE_DIR = ".xla_cache"
_xla_cache_lock = threading.Lock()
_xla_cache_dir: Optional[str] = None


def enable_xla_compile_cache(cache_dir: Optional[str] = None,
                             ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (flag --xla-cache-dir / env KYVERNO_TPU_XLA_CACHE_DIR, default
    ``.xla_cache`` under the working directory). Compiled ``device_fn``
    programs then survive process restarts: a serve restart or the
    bench probe warm-starts in seconds instead of re-paying the full
    XLA build. ``none``/``off``/empty disables. Idempotent; returns
    the active directory or None when disabled.

    An unwritable cache dir (read-only/full disk) NEVER fails a
    compile: the persistent cache is simply not enabled — one op-log
    event, the ``xla_cache`` storage surface degrades, and every
    compile runs warm-start-less but correct. The writability check is
    a real probe-file write: ``makedirs(exist_ok=True)`` succeeds on an
    existing dir even on a read-only filesystem."""
    from ..resilience import storage as stg

    global _xla_cache_dir
    if cache_dir is None:
        cache_dir = os.environ.get("KYVERNO_TPU_XLA_CACHE_DIR",
                                   DEFAULT_XLA_CACHE_DIR)
    if not cache_dir or cache_dir.lower() in ("none", "off", "disabled"):
        return None
    cache_dir = os.path.abspath(cache_dir)
    with _xla_cache_lock:
        if _xla_cache_dir == cache_dir:
            return cache_dir
        import jax

        try:
            stg.makedirs(cache_dir, stg.SURFACE_XLA_CACHE)
            stg.probe_writable(cache_dir, stg.SURFACE_XLA_CACHE)
        except OSError:
            # degraded + counted by the shim; announce the single
            # consequence (no warm starts) and keep compiling
            try:
                from ..observability.log import global_oplog

                global_oplog.emit("xla_cache_disabled", level="warn",
                                  dir=cache_dir)
            except Exception:
                pass
            return None
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip small/fast programs; a policy set's
        # device_fn at MIN_BUCKET can compile fast on CPU yet still be
        # worth persisting (the probe's whole point is a warm start)
        for knob, value in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, value)
            except Exception:  # knob absent on this jax version
                pass
        _xla_cache_dir = cache_dir
    return cache_dir


def xla_cache_dir() -> Optional[str]:
    return _xla_cache_dir
