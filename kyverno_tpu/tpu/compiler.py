"""Policy set -> compiled device artifact.

compile_policy_set lowers every validate rule of every policy through
the IR compiler (ir.py). Rules using constructs outside the device
subset are recorded as host rules — the TpuEngine completes their
verdicts with the scalar engine, so a compiled set always covers the
full policy list (device where possible, host elsewhere).

The compiled artifact is keyed by the policy set content; recompiling
only happens when policies change (the reference's analogous concern is
webhook/policycache refresh on policy resourceVersion changes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import jax

from ..api.policy import ClusterPolicy, Rule
from ..engine.operator import Operator
from ..utils.wildcard import contains_wildcard
from .dfa import DfaBank, DfaUnsupported, state_budget
from .evaluator import build_program
from .flatten import EncodeConfig, plan_byte_pool
from .ir import (
    ArrayMapsNode,
    ArrayScalarNode,
    DynKey,
    DynSlot,
    DynValueRef,
    ExistenceNode,
    LeafNode,
    MapNode,
    RuleProgram,
    StrLeaf,
    Unsupported,
    compile_rule,
)
from .metadata import MetaConfig
# the mutation package only pulls api/engine-level modules — no cycle
from ..mutation.lowering import (PatchTemplate, lower_mutate_rule,
                                 paths_conflict, rule_read_paths,
                                 rule_write_paths)
from ..mutation.triage import triage_rule


def _iter_cond_irs(prog: RuleProgram):
    """Every CondIR in a program's precondition/deny/foreach trees."""
    trees = [prog.preconditions, prog.deny] + [f.tree for f in prog.foreach]
    for tree in trees:
        if tree is None:
            continue
        for any_block, all_block in tree.blocks:
            yield from any_block
            yield from all_block


@dataclass
class RuleEntry:
    policy_idx: int
    policy_name: str
    rule_name: str
    device_row: Optional[int]      # row in the device verdict table
    fallback_reason: Optional[str]  # set for host rules

    @property
    def pattern_host(self) -> bool:
        """Host rule whose fallback is pattern-caused (non-lowerable
        regex etc.) — the coverage accounting distinguishes these
        cells from other host cells."""
        return bool(self.fallback_reason
                    and self.fallback_reason.startswith("pattern:"))


# ---------------------------------------------------------------------------
# DFA bank collection: every glob/regex operand a compiled program
# evaluates, registered per byte-lane family (tpu/dfa.py)


def _leaf_glob_operands(leaf) -> List[str]:
    if not isinstance(leaf, StrLeaf):
        return []
    return [c.operand
            for units in leaf.alternatives for unit in units for c in unit
            if c.is_glob and c.operand != "*"
            and c.op in (Operator.EQUAL, Operator.NOT_EQUAL)]


def _walk_pattern_globs(specs: List[Tuple[str, str, str]], node) -> None:
    if node is None:
        return
    if isinstance(node, (LeafNode, ArrayScalarNode)):
        for g in _leaf_glob_operands(node.leaf):
            specs.append(("glob", g, "pool"))
        return
    if isinstance(node, MapNode):
        for a in node.anchors:
            _walk_pattern_globs(specs, a.child)
            _note_wildcard_key(specs, a.wildcard)
        for c in node.phase2:
            _walk_pattern_globs(specs, c.child)
            _note_wildcard_key(specs, c.wildcard)
        return
    if isinstance(node, ArrayMapsNode):
        _walk_pattern_globs(specs, node.element)
        return
    if isinstance(node, ExistenceNode):
        for el in node.elements:
            _walk_pattern_globs(specs, el)


def _note_wildcard_key(specs: List[Tuple[str, str, str]], wc) -> None:
    if wc is None:
        return
    specs.append(("glob", wc.glob, "pool"))  # key bytes share the pool
    for g in _leaf_glob_operands(wc.leaf):
        specs.append(("glob", g, "pool"))


def _program_pattern_specs(prog: RuleProgram) -> List[Tuple[str, str, str]]:
    """Every (kind, pattern, lane-family) a compiled program matches
    through the DFA bank."""
    specs: List[Tuple[str, str, str]] = []
    for root in prog.patterns:
        _walk_pattern_globs(specs, root)
    for block in (prog.match, prog.exclude):
        if block is None:
            continue
        for f in block.filters:
            for nm in ([f.name] if f.name else []) + list(f.names):
                if contains_wildcard(nm):
                    specs.append(("glob", nm, "name"))
            for ns in f.namespaces:
                if contains_wildcard(ns):
                    specs.append(("glob", ns, "ns"))
                    # Namespace-kind resources compare their NAME
                    specs.append(("glob", ns, "name"))
            if f.selector is not None:
                for k_pat, v_pat in getattr(f.selector, "wild_labels", ()):
                    specs.append(("glob", k_pat, "labels_kb"))
                    specs.append(("glob", v_pat, "labels_vb"))
    for rx in prog.regex_patterns:
        specs.append(("re2", rx, "pool"))
    return specs


def _register_program_patterns(bank: DfaBank, prog: RuleProgram,
                               owner: Optional[str] = None) -> bool:
    """Register a program's patterns; returns whether it has any
    (pattern-cell accounting rides prog.uses_patterns). ``owner``
    attributes the patterns to a policy/rule for /debug/rules."""
    specs = _program_pattern_specs(prog)
    for kind, pattern, family in specs:
        if kind == "re2":
            bank.add_re2(pattern, family, owner=owner)
        else:
            bank.add_glob(pattern, family, owner=owner)
    return bool(specs)


@dataclass
class CompiledPolicySet:
    policies: List[ClusterPolicy]
    rules: List[RuleEntry]
    device_programs: List[RuleProgram]
    byte_paths: Set[int]
    key_byte_paths: Set[int]
    encode_cfg: EncodeConfig
    meta_cfg: MetaConfig
    # compile-time context specialization: configmaps folded into the
    # programs, "namespace/name" -> content hash at compile. A program
    # is only valid while every dep's hash is unchanged (scanner
    # recompiles on movement).
    context_deps: Dict[str, Optional[str]] = field(default_factory=dict)
    # global host-resolved operand slots (per-request context values
    # feeding the device program as canonical lanes)
    dyn_slots: List[DynSlot] = field(default_factory=list)
    # lifecycle quarantine: policy indices excluded from lowering
    # (their rules are host-fallback RuleEntries tagged "quarantined:"),
    # with the compile error that put them there
    quarantined: Dict[int, str] = field(default_factory=dict)
    # the policy set's compiled pattern tables (tpu/dfa.py): every
    # glob/regex operand as one DFA in a packed bank, evaluated by the
    # device program in one scan per byte-lane family
    dfa: Optional[DfaBank] = None
    # mutate-rule bank (mutation/): one RuleEntry per mutate rule in
    # policy order, device_row indexing mutate_programs (the compiled
    # needs-mutation triage predicates), with a parallel list of
    # lowered patch templates (None = scalar patcher when positive)
    mutate_entries: List[RuleEntry] = field(default_factory=list)
    mutate_programs: List[RuleProgram] = field(default_factory=list)
    mutate_templates: List[Optional[PatchTemplate]] = field(default_factory=list)
    _fn: Optional[Callable] = field(default=None, repr=False)
    _mutate_fn: Optional[Callable] = field(default=None, repr=False)
    _cache_key: Optional[str] = field(default=None, repr=False)
    _policy_spec_hashes: Optional[List[str]] = field(default=None, repr=False)

    @property
    def host_rule_policies(self) -> List[int]:
        """Policy indices owning at least one host-fallback rule."""
        return sorted({e.policy_idx for e in self.rules if e.device_row is None})

    def device_fn(self) -> Callable:
        """The jitted batch program (compiled lazily, cached),
        returning (verdict table, per-rule verdict-class counts) — the
        counts are the device-side rule-analytics reduction
        (evaluator.build_program with_counts). Every lookup is
        attributed on kyverno_tpu_compile_cache_total so the hit/miss
        ratio — the recompilation-churn signal SURVEY §7 warns about —
        is scrapeable, not inferred from latency spikes."""
        from ..observability.metrics import global_registry
        from ..observability.profiling import PHASE_COMPILE, global_profiler
        from ..observability.tracing import global_tracer

        if self._fn is None:
            global_registry.compile_cache.inc({"outcome": "miss"})
            with global_profiler.phase(PHASE_COMPILE), \
                    global_tracer.span("xla_jit_build",
                                       programs=len(self.device_programs)):
                self._fn = jax.jit(
                    build_program(self.device_programs,
                                  self.encode_cfg.max_instances,
                                  with_counts=True, dfa=self.dfa)
                )
        else:
            global_registry.compile_cache.inc({"outcome": "hit"})
        return self._fn

    @property
    def mutate_rules(self) -> List[Tuple[str, str]]:
        """Bank-ordered (policy_name, rule_name) idents — the row
        identity shared by triage verdicts, templates, and the
        coordinator."""
        return [(e.policy_name, e.rule_name) for e in self.mutate_entries]

    def mutate_device_fn(self) -> Callable:
        """The jitted triage batch program over the mutate bank — same
        shape contract as device_fn minus the analytics counts (triage
        rows feed routing, not rule stats)."""
        from ..observability.profiling import PHASE_COMPILE, global_profiler
        from ..observability.tracing import global_tracer

        if self._mutate_fn is None:
            with global_profiler.phase(PHASE_COMPILE), \
                    global_tracer.span("xla_jit_build_mutate",
                                       programs=len(self.mutate_programs)):
                self._mutate_fn = jax.jit(
                    build_program(self.mutate_programs,
                                  self.encode_cfg.max_instances,
                                  with_counts=False, dfa=self.dfa)
                )
        return self._mutate_fn

    def mutate_coverage(self) -> Tuple[int, int]:
        dev = sum(1 for e in self.mutate_entries
                  if e.device_row is not None)
        return dev, len(self.mutate_entries)

    def policy_spec_hashes(self) -> List[str]:
        """Per-policy analytics identity (spec-content hash), memoized
        — RuleStatsAccumulator keys rule rows with these so stats
        survive snapshot swaps and renames."""
        if self._policy_spec_hashes is None:
            from ..observability.analytics import policy_spec_hash

            self._policy_spec_hashes = [policy_spec_hash(p)
                                        for p in self.policies]
        return self._policy_spec_hashes

    def coverage(self) -> Tuple[int, int]:
        dev = sum(1 for e in self.rules if e.device_row is not None)
        return dev, len(self.rules)

    def publish_dfa_gauges(self) -> None:
        """Point the bank-size gauges at THIS set. Called when a set
        becomes the serving artifact (engine construction, lifecycle
        swap) — NOT on every compile, so probe/bisect/baseline
        compiles never clobber the active set's numbers."""
        if self.dfa is None:
            return
        try:
            from ..observability.metrics import global_registry as _reg

            stats = self.dfa.stats()
            _reg.dfa_tables.set(stats["tables"])
            _reg.dfa_states.set(stats["states"])
            _reg.dfa_bytes.set(stats["bytes"])
            for k, n in stats["stride_hist"].items():
                _reg.dfa_stride_tables.set(n, {"stride": k})
            _reg.dfa_stride_bytes.set(stats["stride_bytes"])
            _reg.dfa_approx_states_merged.set(stats["states_merged"])
            _reg.dfa_approx_error_max.set(stats["max_approx_error"])
        except Exception:  # noqa: BLE001
            pass  # metrics must never block the serving path

    def cache_key(self) -> str:
        """Content identity of this compiled artifact — the policy-set
        half of every verdict-cache key (tpu/cache.py). Covers
        everything that can change a verdict column for a fixed
        (resource, request): policy content, quarantine set, encode and
        metadata caps, and the content hashes of every compile-folded
        context dependency — so a configmap moving under a specialized
        program rotates the key instead of serving stale verdicts."""
        if self._cache_key is None:
            from ..lifecycle.snapshot import policy_content_hash
            from .cache import digest

            self._cache_key = digest(
                [policy_content_hash(p) for p in self.policies],
                sorted(self.quarantined.items()),
                sorted(self.context_deps.items()),
                (self.encode_cfg.max_rows, self.encode_cfg.max_instances,
                 self.encode_cfg.byte_pool_slots,
                 self.encode_cfg.byte_pool_width),
                sorted(vars(self.meta_cfg).items()),
                sorted(self.byte_paths), sorted(self.key_byte_paths),
                # the DFA state budget changes tables (and the confirm
                # ladder) without changing policy content — the bank
                # digest rotates verdict-cache keys when it moves
                self.dfa.digest() if self.dfa is not None else "")
        return self._cache_key


def compile_policy_set(
    policies: Sequence[ClusterPolicy],
    encode_cfg: Optional[EncodeConfig] = None,
    meta_cfg: Optional[MetaConfig] = None,
    data_sources=None,
    quarantine: Optional[Dict[int, str]] = None,
) -> CompiledPolicySet:
    """``quarantine`` maps policy indices the lifecycle manager has
    quarantined (their last compile CRASHED, not merely Unsupported) to
    the error string; their rules skip lowering entirely and become
    host-fallback entries, so the rest of the set still runs on the
    device while the quarantined policy degrades to the scalar oracle
    (per-rule ERROR when even the oracle cannot evaluate it)."""
    from ..observability.profiling import PHASE_COMPILE, global_profiler
    from ..observability.tracing import global_tracer

    with global_profiler.phase(PHASE_COMPILE), \
            global_tracer.span("policy_set_compile", policies=len(policies),
                               quarantined=len(quarantine or ())):
        return _compile_policy_set(policies, encode_cfg, meta_cfg,
                                   data_sources, quarantine)


def _compile_policy_set(
    policies: Sequence[ClusterPolicy],
    encode_cfg: Optional[EncodeConfig] = None,
    meta_cfg: Optional[MetaConfig] = None,
    data_sources=None,
    quarantine: Optional[Dict[int, str]] = None,
) -> CompiledPolicySet:
    encode_cfg = encode_cfg or EncodeConfig()
    meta_cfg = meta_cfg or MetaConfig()
    quarantine = dict(quarantine or {})
    entries: List[RuleEntry] = []
    programs: List[RuleProgram] = []
    byte_paths: Set[int] = set()
    key_byte_paths: Set[int] = set()
    deps: Dict[str, Optional[str]] = {}
    dyn_slots: List[DynSlot] = []
    bank = DfaBank(state_budget())
    for pi, policy in enumerate(policies):
        q_err = quarantine.get(pi)
        for rule in policy.get_rules():
            if not rule.has_validate():
                continue
            if q_err is not None:
                entries.append(RuleEntry(pi, policy.name, rule.name, None,
                                         f"quarantined: {q_err}"))
                continue
            try:
                prog = compile_rule(policy, rule, data_sources, deps)
                # register the rule's patterns with the bank BEFORE
                # committing the program: a full bank demotes the rule
                # to host instead of compiling an unevaluable program
                try:
                    prog.uses_patterns = _register_program_patterns(
                        bank, prog, owner=f"{policy.name}/{rule.name}")
                except DfaUnsupported as e:
                    raise Unsupported(f"pattern: {e}")
                row = len(programs)
                if prog.dyn_slots:
                    # rebase rule-local operand slots onto the global
                    # slot table the runtime fills per batch
                    base = len(dyn_slots)
                    dyn_slots.extend(prog.dyn_slots)
                    for ir_cond in _iter_cond_irs(prog):
                        if isinstance(ir_cond.key, DynKey):
                            ir_cond.key.slot += base
                        if isinstance(ir_cond.value, DynValueRef):
                            ir_cond.value.slot += base
                programs.append(prog)
                byte_paths |= prog.byte_paths
                key_byte_paths |= prog.key_byte_paths
                entries.append(RuleEntry(pi, policy.name, rule.name, row, None))
            except Unsupported as e:
                entries.append(RuleEntry(pi, policy.name, rule.name, None, str(e)))
    # mutate-rule bank: the same lowering ladder for needs-mutation
    # triage predicates. Pass 1 walks policy order demoting
    # chain-dependent predicates to host (an earlier mutate rule may
    # write a path this rule's predicate reads; the scalar chain
    # evaluates against patched-so-far, device triage against the
    # ORIGINAL — triaging such a rule on device would be unsound).
    # Pass 2 compiles the survivors through the same IR path as
    # validate, sharing the DFA bank and byte-path planning.
    mutate_entries: List[RuleEntry] = []
    mutate_programs: List[RuleProgram] = []
    mutate_templates: List[Optional[PatchTemplate]] = []
    collected: List[Tuple[int, ClusterPolicy, Rule, bool]] = []
    writes_so_far: List = []
    for pi, policy in enumerate(policies):
        for rule in policy.get_rules():
            if not rule.has_mutate():
                continue
            reads = rule_read_paths(rule)
            conflict = any(paths_conflict(w, reads) for w in writes_so_far)
            collected.append((pi, policy, rule, conflict))
            # demoted rules still WRITE — later predicates must see them
            writes_so_far.append(rule_write_paths(rule))
    for pi, policy, rule, conflict in collected:
        tmpl = lower_mutate_rule(rule)
        if tmpl is not None:
            tmpl.policy_name = policy.name
        mutate_templates.append(tmpl)
        q_err = quarantine.get(pi)
        if q_err is not None:
            mutate_entries.append(RuleEntry(pi, policy.name, rule.name, None,
                                            f"quarantined: {q_err}"))
            continue
        if conflict:
            mutate_entries.append(RuleEntry(
                pi, policy.name, rule.name, None,
                "chain-dependent: an earlier mutate rule may write a "
                "path this rule's predicate reads"))
            continue
        try:
            prog = compile_rule(policy, triage_rule(rule),
                                data_sources, deps)
            if prog.dyn_slots:
                # triage must not push operand slots into the shared
                # slot table — that would flip the validate bank's
                # cache eligibility. Host-route instead.
                raise Unsupported("context: dynamic operand slots")
            try:
                prog.uses_patterns = _register_program_patterns(
                    bank, prog, owner=f"{policy.name}/{rule.name}")
            except DfaUnsupported as e:
                raise Unsupported(f"pattern: {e}")
            row = len(mutate_programs)
            mutate_programs.append(prog)
            byte_paths |= prog.byte_paths
            key_byte_paths |= prog.key_byte_paths
            mutate_entries.append(RuleEntry(pi, policy.name, rule.name,
                                            row, None))
        except Unsupported as e:
            mutate_entries.append(RuleEntry(pi, policy.name, rule.name,
                                            None, str(e)))
        except Exception as e:  # noqa: BLE001 — a triage compile crash
            # must never fail a policy set that compiled before this
            # bank existed; the rule degrades to host triage
            mutate_entries.append(RuleEntry(pi, policy.name, rule.name,
                                            None,
                                            f"triage compile error: {e}"))
    # dense (un-pruned) encodes only pay for label byte lanes when some
    # compiled selector actually globs. The flag lives on a COPY: the
    # caller's MetaConfig may be shared across compiles, and a later
    # compile must not mutate an earlier compiled set's config.
    import copy as _copy

    meta_cfg = _copy.copy(meta_cfg)
    meta_cfg.label_bytes_enabled = any(
        getattr(sel, "wild_labels", None)
        for prog in programs + mutate_programs
        for block in (prog.match, prog.exclude) if block is not None
        for f in block.filters
        for sel in (f.selector, f.ns_selector) if sel is not None)
    bank.finalize()
    # byte-lane capacity planning: pattern-referenced paths need pool
    # slots; a pattern-heavy set grows the pool instead of flagging
    # every resource into host fallback (the cfg copy keeps the
    # caller's shared EncodeConfig untouched, like meta_cfg above)
    encode_cfg = plan_byte_pool(encode_cfg, byte_paths, key_byte_paths)
    return CompiledPolicySet(
        policies=list(policies),
        rules=entries,
        device_programs=programs,
        byte_paths=byte_paths,
        key_byte_paths=key_byte_paths,
        encode_cfg=encode_cfg,
        meta_cfg=meta_cfg,
        context_deps=deps,
        dyn_slots=dyn_slots,
        quarantined=quarantine,
        dfa=bank,
        mutate_entries=mutate_entries,
        mutate_programs=mutate_programs,
        mutate_templates=mutate_templates,
    )
