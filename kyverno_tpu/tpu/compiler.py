"""Policy set -> compiled device artifact.

compile_policy_set lowers every validate rule of every policy through
the IR compiler (ir.py). Rules using constructs outside the device
subset are recorded as host rules — the TpuEngine completes their
verdicts with the scalar engine, so a compiled set always covers the
full policy list (device where possible, host elsewhere).

The compiled artifact is keyed by the policy set content; recompiling
only happens when policies change (the reference's analogous concern is
webhook/policycache refresh on policy resourceVersion changes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import jax

from ..api.policy import ClusterPolicy, Rule
from .evaluator import build_program
from .flatten import EncodeConfig
from .ir import DynKey, DynSlot, DynValueRef, RuleProgram, Unsupported, compile_rule
from .metadata import MetaConfig


def _iter_cond_irs(prog: RuleProgram):
    """Every CondIR in a program's precondition/deny/foreach trees."""
    trees = [prog.preconditions, prog.deny] + [f.tree for f in prog.foreach]
    for tree in trees:
        if tree is None:
            continue
        for any_block, all_block in tree.blocks:
            yield from any_block
            yield from all_block


@dataclass
class RuleEntry:
    policy_idx: int
    policy_name: str
    rule_name: str
    device_row: Optional[int]      # row in the device verdict table
    fallback_reason: Optional[str]  # set for host rules


@dataclass
class CompiledPolicySet:
    policies: List[ClusterPolicy]
    rules: List[RuleEntry]
    device_programs: List[RuleProgram]
    byte_paths: Set[int]
    key_byte_paths: Set[int]
    encode_cfg: EncodeConfig
    meta_cfg: MetaConfig
    # compile-time context specialization: configmaps folded into the
    # programs, "namespace/name" -> content hash at compile. A program
    # is only valid while every dep's hash is unchanged (scanner
    # recompiles on movement).
    context_deps: Dict[str, Optional[str]] = field(default_factory=dict)
    # global host-resolved operand slots (per-request context values
    # feeding the device program as canonical lanes)
    dyn_slots: List[DynSlot] = field(default_factory=list)
    # lifecycle quarantine: policy indices excluded from lowering
    # (their rules are host-fallback RuleEntries tagged "quarantined:"),
    # with the compile error that put them there
    quarantined: Dict[int, str] = field(default_factory=dict)
    _fn: Optional[Callable] = field(default=None, repr=False)
    _cache_key: Optional[str] = field(default=None, repr=False)
    _policy_spec_hashes: Optional[List[str]] = field(default=None, repr=False)

    @property
    def host_rule_policies(self) -> List[int]:
        """Policy indices owning at least one host-fallback rule."""
        return sorted({e.policy_idx for e in self.rules if e.device_row is None})

    def device_fn(self) -> Callable:
        """The jitted batch program (compiled lazily, cached),
        returning (verdict table, per-rule verdict-class counts) — the
        counts are the device-side rule-analytics reduction
        (evaluator.build_program with_counts). Every lookup is
        attributed on kyverno_tpu_compile_cache_total so the hit/miss
        ratio — the recompilation-churn signal SURVEY §7 warns about —
        is scrapeable, not inferred from latency spikes."""
        from ..observability.metrics import global_registry
        from ..observability.profiling import PHASE_COMPILE, global_profiler
        from ..observability.tracing import global_tracer

        if self._fn is None:
            global_registry.compile_cache.inc({"outcome": "miss"})
            with global_profiler.phase(PHASE_COMPILE), \
                    global_tracer.span("xla_jit_build",
                                       programs=len(self.device_programs)):
                self._fn = jax.jit(
                    build_program(self.device_programs,
                                  self.encode_cfg.max_instances,
                                  with_counts=True)
                )
        else:
            global_registry.compile_cache.inc({"outcome": "hit"})
        return self._fn

    def policy_spec_hashes(self) -> List[str]:
        """Per-policy analytics identity (spec-content hash), memoized
        — RuleStatsAccumulator keys rule rows with these so stats
        survive snapshot swaps and renames."""
        if self._policy_spec_hashes is None:
            from ..observability.analytics import policy_spec_hash

            self._policy_spec_hashes = [policy_spec_hash(p)
                                        for p in self.policies]
        return self._policy_spec_hashes

    def coverage(self) -> Tuple[int, int]:
        dev = sum(1 for e in self.rules if e.device_row is not None)
        return dev, len(self.rules)

    def cache_key(self) -> str:
        """Content identity of this compiled artifact — the policy-set
        half of every verdict-cache key (tpu/cache.py). Covers
        everything that can change a verdict column for a fixed
        (resource, request): policy content, quarantine set, encode and
        metadata caps, and the content hashes of every compile-folded
        context dependency — so a configmap moving under a specialized
        program rotates the key instead of serving stale verdicts."""
        if self._cache_key is None:
            from ..lifecycle.snapshot import policy_content_hash
            from .cache import digest

            self._cache_key = digest(
                [policy_content_hash(p) for p in self.policies],
                sorted(self.quarantined.items()),
                sorted(self.context_deps.items()),
                (self.encode_cfg.max_rows, self.encode_cfg.max_instances,
                 self.encode_cfg.byte_pool_slots,
                 self.encode_cfg.byte_pool_width),
                sorted(vars(self.meta_cfg).items()),
                sorted(self.byte_paths), sorted(self.key_byte_paths))
        return self._cache_key


def compile_policy_set(
    policies: Sequence[ClusterPolicy],
    encode_cfg: Optional[EncodeConfig] = None,
    meta_cfg: Optional[MetaConfig] = None,
    data_sources=None,
    quarantine: Optional[Dict[int, str]] = None,
) -> CompiledPolicySet:
    """``quarantine`` maps policy indices the lifecycle manager has
    quarantined (their last compile CRASHED, not merely Unsupported) to
    the error string; their rules skip lowering entirely and become
    host-fallback entries, so the rest of the set still runs on the
    device while the quarantined policy degrades to the scalar oracle
    (per-rule ERROR when even the oracle cannot evaluate it)."""
    from ..observability.profiling import PHASE_COMPILE, global_profiler
    from ..observability.tracing import global_tracer

    with global_profiler.phase(PHASE_COMPILE), \
            global_tracer.span("policy_set_compile", policies=len(policies),
                               quarantined=len(quarantine or ())):
        return _compile_policy_set(policies, encode_cfg, meta_cfg,
                                   data_sources, quarantine)


def _compile_policy_set(
    policies: Sequence[ClusterPolicy],
    encode_cfg: Optional[EncodeConfig] = None,
    meta_cfg: Optional[MetaConfig] = None,
    data_sources=None,
    quarantine: Optional[Dict[int, str]] = None,
) -> CompiledPolicySet:
    encode_cfg = encode_cfg or EncodeConfig()
    meta_cfg = meta_cfg or MetaConfig()
    quarantine = dict(quarantine or {})
    entries: List[RuleEntry] = []
    programs: List[RuleProgram] = []
    byte_paths: Set[int] = set()
    key_byte_paths: Set[int] = set()
    deps: Dict[str, Optional[str]] = {}
    dyn_slots: List[DynSlot] = []
    for pi, policy in enumerate(policies):
        q_err = quarantine.get(pi)
        for rule in policy.get_rules():
            if not rule.has_validate():
                continue
            if q_err is not None:
                entries.append(RuleEntry(pi, policy.name, rule.name, None,
                                         f"quarantined: {q_err}"))
                continue
            try:
                prog = compile_rule(policy, rule, data_sources, deps)
                row = len(programs)
                if prog.dyn_slots:
                    # rebase rule-local operand slots onto the global
                    # slot table the runtime fills per batch
                    base = len(dyn_slots)
                    dyn_slots.extend(prog.dyn_slots)
                    for ir_cond in _iter_cond_irs(prog):
                        if isinstance(ir_cond.key, DynKey):
                            ir_cond.key.slot += base
                        if isinstance(ir_cond.value, DynValueRef):
                            ir_cond.value.slot += base
                programs.append(prog)
                byte_paths |= prog.byte_paths
                key_byte_paths |= prog.key_byte_paths
                entries.append(RuleEntry(pi, policy.name, rule.name, row, None))
            except Unsupported as e:
                entries.append(RuleEntry(pi, policy.name, rule.name, None, str(e)))
    # dense (un-pruned) encodes only pay for label byte lanes when some
    # compiled selector actually globs. The flag lives on a COPY: the
    # caller's MetaConfig may be shared across compiles, and a later
    # compile must not mutate an earlier compiled set's config.
    import copy as _copy

    meta_cfg = _copy.copy(meta_cfg)
    meta_cfg.label_bytes_enabled = any(
        getattr(sel, "wild_labels", None)
        for prog in programs
        for block in (prog.match, prog.exclude) if block is not None
        for f in block.filters
        for sel in (f.selector, f.ns_selector) if sel is not None)
    return CompiledPolicySet(
        policies=list(policies),
        rules=entries,
        device_programs=programs,
        byte_paths=byte_paths,
        key_byte_paths=key_byte_paths,
        encode_cfg=encode_cfg,
        meta_cfg=meta_cfg,
        context_deps=deps,
        dyn_slots=dyn_slots,
        quarantined=quarantine,
    )
