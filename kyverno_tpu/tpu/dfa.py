"""Pattern classes -> dense DFA transition tables over byte lanes.

The device engine historically evaluated glob operands with a
bit-parallel NFA unrolled at trace time: one ``lax.scan`` with
O(pattern_len) boolean state columns PER DISTINCT PATTERN
(evaluator.glob_match). That shape is linear in patterns twice — XLA
program size and device work both grow with (patterns x positions) —
and regex patterns (CEL ``matches()``) had no device story at all,
keeping whole rules on the host path.

This module compiles the pattern classes the engine already parses —
``utils/wildcard`` globs, the tractable subset of ``cel/re2.py``
regexes — into dense DFA transition tables stepped as batched table
lookups (the Hyperflex SIMD-DFA model, arXiv:2512.07123): one
``(states x alphabet)`` uint16 table per pattern, alphabet compressed
to per-pattern byte classes, all tables of a policy set concatenated
into ONE bank evaluated in ONE ``lax.scan`` over the byte lanes —
every (pattern x string-lane) pair in a single fused dispatch.

Exactness ladder (approximate-reduction, arXiv:1710.08647):

- DFAs are built by subset construction under a per-pattern state
  budget. A pattern that blows the budget gets an OVER-approximating
  reduced DFA (overflow states collapse into an accept-all TOP state):
  a device MISS is definitive, a device HIT is confirmed by the scalar
  oracle — so approximation costs confirmation work on the rare hits,
  never correctness.
- Tables run over UTF-8 BYTES while the host oracles match CODEPOINTS.
  For pure-ASCII subjects the two are identical; patterns whose
  semantics can differ on multi-byte subjects (``?`` globs — one char
  vs one byte — and every regex) carry ``confirm_nonascii``: subjects
  containing a byte >= 0x80 route to oracle confirmation regardless of
  the DFA verdict. ``*``-only ASCII-literal globs are byte-exact for
  ALL subjects and skip the ladder entirely.

Genuinely non-lowerable patterns (word boundaries, multiline anchors,
lookaround — which cel/re2.py itself rejects) raise
:class:`DfaUnsupported` and keep today's host route.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..cel.re2 import (
    A_BOT,
    A_EOT,
    Re2Error,
    _NFA,
    _Parser,
    _compile as _re2_nfa_compile,
)

__all__ = [
    "Dfa", "DfaBank", "DfaUnsupported", "compile_glob", "compile_re2",
    "bank_match", "nonascii_mask", "state_budget",
]


class DfaUnsupported(Exception):
    """Pattern outside the lowerable subset -> host route."""


DEFAULT_STATE_BUDGET = 192
# total bank states must index as uint16 with headroom
MAX_BANK_STATES = 60000


def state_budget() -> int:
    """Per-pattern DFA state budget (the approximate-reduction knob):
    exact subset construction up to this many states, over-approximating
    TOP-collapse beyond it. serve --dfa-state-budget / env override."""
    try:
        return max(4, int(os.environ.get("KYVERNO_TPU_DFA_STATE_BUDGET",
                                         str(DEFAULT_STATE_BUDGET))))
    except ValueError:
        return DEFAULT_STATE_BUDGET


@dataclass
class Dfa:
    """One compiled pattern: dense transition table over byte classes.

    ``trans`` is (n_states, n_classes) int32 with LOCAL state ids;
    ``class_map`` maps each byte 0..255 to its column; ``accept`` marks
    accepting states (evaluated at end-of-string — the scan freezes the
    state once the cursor passes the string length)."""

    pattern: str
    kind: str                    # glob | re2
    trans: np.ndarray
    class_map: np.ndarray        # (256,) uint8
    accept: np.ndarray           # (n_states,) bool
    start: int
    exact: bool                  # False => over-approximating (hit -> confirm)
    confirm_nonascii: bool       # byte/codepoint semantics may differ

    @property
    def n_states(self) -> int:
        return int(self.trans.shape[0])

    @property
    def n_classes(self) -> int:
        return int(self.trans.shape[1])

    def match_bytes(self, data: bytes) -> bool:
        """Host-side table walk — the parity/fuzz oracle for the packed
        device kernel (identical table, identical stepping order)."""
        s = self.start
        trans, cmap = self.trans, self.class_map
        for b in data:
            s = int(trans[s, cmap[b]])
        return bool(self.accept[s])

    def match_str(self, text: str) -> bool:
        return self.match_bytes(text.encode("utf-8"))


# ---------------------------------------------------------------------------
# alphabet compression: partition bytes by membership signature

def _byte_classes(predicates: Sequence[FrozenSet[int]]
                  ) -> Tuple[np.ndarray, List[int]]:
    """Bytes indistinguishable by every predicate share a class.
    Returns (class_map (256,) uint8, representative byte per class)."""
    if not predicates:
        return np.zeros(256, dtype=np.uint8), [0]
    member = np.zeros((len(predicates), 256), dtype=bool)
    for i, pred in enumerate(predicates):
        for b in pred:
            member[i, b] = True
    # unique signature columns -> class ids
    _, inverse = np.unique(member.T, axis=0, return_inverse=True)
    class_map = inverse.astype(np.uint8)
    reps: List[int] = []
    seen: Dict[int, int] = {}
    for b in range(256):
        c = int(class_map[b])
        if c not in seen:
            seen[c] = b
    for c in range(int(class_map.max()) + 1):
        reps.append(seen[c])
    return class_map, reps


class _Determinizer:
    """Budgeted subset construction. Overflow states collapse into one
    accept-all TOP state (over-approximation: miss stays definitive)."""

    def __init__(self, n_classes: int, budget: int):
        self.n_classes = n_classes
        self.budget = budget
        self.ids: Dict[object, int] = {}
        self.trans: List[List[int]] = []
        self.accept: List[bool] = []
        self.exact = True
        self._top: Optional[int] = None

    def top(self) -> int:
        if self._top is None:
            self._top = len(self.trans)
            self.trans.append([self._top] * self.n_classes)
            self.accept.append(True)
        return self._top

    def intern(self, key) -> Tuple[int, bool]:
        """(state id, is_new). Over budget -> TOP, exact=False."""
        sid = self.ids.get(key)
        if sid is not None:
            return sid, False
        if len(self.trans) >= self.budget:
            self.exact = False
            return self.top(), False
        sid = len(self.trans)
        self.ids[key] = sid
        self.trans.append([0] * self.n_classes)
        self.accept.append(False)
        return sid, True


# ---------------------------------------------------------------------------
# glob -> DFA (anchored full match, go-wildcard semantics over bytes)

def _glob_elems(pattern: str) -> List[Tuple]:
    elems: List[Tuple] = []
    for ch in pattern:
        if ch == "*":
            if elems and elems[-1][0] == "star":
                continue
            elems.append(("star",))
        elif ch == "?":
            elems.append(("any",))
        else:
            for b in ch.encode("utf-8"):
                elems.append(("byte", b))
    return elems


# compiled-table memo: subset construction runs once per (pattern,
# budget) per process, not once per policy-set compile — the IR
# lowering probes compile_re2 for lowerability and the bank compiles
# the same pattern again, and lifecycle compile-ahead / quarantine
# bisect recompile whole sets repeatedly. Dfa instances are
# read-only-by-convention and safely shared across banks.
_DFA_MEMO: Dict[Tuple[str, str, int], "Dfa"] = {}
_DFA_MEMO_CAP = 1024


def _memoized(kind: str, pattern: str, budget: int, build) -> "Dfa":
    key = (kind, pattern, budget)
    dfa = _DFA_MEMO.get(key)
    if dfa is None:
        dfa = build()
        if len(_DFA_MEMO) >= _DFA_MEMO_CAP:
            _DFA_MEMO.clear()
        _DFA_MEMO[key] = dfa
    return dfa


def compile_glob(pattern: str, budget: Optional[int] = None) -> Dfa:
    budget = budget or state_budget()
    return _memoized("glob", pattern, budget,
                     lambda: _compile_glob(pattern, budget))


def _compile_glob(pattern: str, budget: int) -> Dfa:
    elems = _glob_elems(pattern)
    m = len(elems)

    def close(posns: Set[int]) -> FrozenSet[int]:
        out = set(posns)
        stack = list(posns)
        while stack:
            j = stack.pop()
            if j < m and elems[j][0] == "star" and j + 1 not in out:
                out.add(j + 1)
                stack.append(j + 1)
        return frozenset(out)

    lits = sorted({e[1] for e in elems if e[0] == "byte"})
    predicates = [frozenset((b,)) for b in lits]
    has_any = any(e[0] in ("any", "star") for e in elems)
    if has_any:
        predicates.append(frozenset(range(256)))
    class_map, reps = _byte_classes(predicates)

    det = _Determinizer(len(reps), budget)
    start_set = close({0})
    start, _ = det.intern(start_set)
    det.accept[start] = m in start_set
    work = [(start, start_set)]
    while work:
        sid, S = work.pop()
        for c, rb in enumerate(reps):
            moved: Set[int] = set()
            for j in S:
                if j >= m:
                    continue
                k, *payload = elems[j]
                if k == "byte":
                    if payload[0] == rb:
                        moved.add(j + 1)
                elif k == "any":
                    moved.add(j + 1)
                else:  # star: consumes any byte, stays (closure adds j+1)
                    moved.add(j)
            nset = close(moved)
            nid, fresh = det.intern(nset)
            det.trans[sid][c] = nid
            if fresh:
                det.accept[nid] = m in nset
                work.append((nid, nset))
    return Dfa(
        pattern=pattern, kind="glob",
        trans=np.asarray(det.trans, dtype=np.int32).reshape(
            len(det.trans), det.n_classes),
        class_map=class_map,
        accept=np.asarray(det.accept, dtype=bool),
        start=start, exact=det.exact,
        confirm_nonascii=("?" in pattern),
    )


# ---------------------------------------------------------------------------
# re2 subset -> DFA (unanchored search, cel matches() semantics)

def _charset_bytes(cs) -> FrozenSet[int]:
    """ASCII bytes the charset matches exactly, plus the 0x80-0xFF lump
    whenever the set can match any non-ASCII codepoint (subjects with
    such bytes confirm on the oracle anyway — see module docstring)."""
    out = {b for b in range(128) if cs.matches(chr(b))}
    if cs.ci:
        high = True  # case folds can cross the ASCII boundary
    elif cs.negated:
        # negation matches some codepoint >= 128 unless the ranges
        # cover [128, 0x10FFFF] completely
        cursor = 128
        for lo, hi in sorted(cs.ranges):
            if hi < cursor:
                continue
            if lo > cursor:
                break
            cursor = hi + 1
        high = cursor <= 0x10FFFF
    else:
        high = any(hi >= 128 for _, hi in cs.ranges)
    if high:
        out |= set(range(128, 256))
    return frozenset(out)


def compile_re2(pattern: str, budget: Optional[int] = None) -> Dfa:
    """Compile a cel/re2.py pattern into a search DFA (partial-match
    semantics: the byte automaton re-seeds the NFA start at every
    position, acceptance is sticky). Raises DfaUnsupported for
    constructs byte tables cannot carry (word boundaries, multiline
    anchors) — and Re2Error propagates for non-RE2 syntax."""
    budget = budget or state_budget()
    return _memoized("re2", pattern, budget,
                     lambda: _compile_re2(pattern, budget))


def _compile_re2(pattern: str, budget: int) -> Dfa:
    try:
        ast = _Parser(pattern).parse()
    except Re2Error:
        raise
    nfa = _NFA()
    accept_id = nfa.state()
    nfa_start = _re2_nfa_compile(nfa, ast, accept_id)
    for a in nfa.asserts:
        if a is not None and a not in (A_BOT, A_EOT):
            raise DfaUnsupported(
                f"assertion {a} (word boundary / multiline anchor) has no "
                f"byte-DFA lowering")

    char_states = [s for s in range(len(nfa.chars))
                   if nfa.chars[s] is not None]
    byteset: Dict[int, FrozenSet[int]] = {
        s: _charset_bytes(nfa.chars[s]) for s in char_states}
    class_map, reps = _byte_classes(list(byteset.values()))

    def closure(raw: FrozenSet[int], at_start: bool, at_end: bool
                ) -> Tuple[FrozenSet[int], bool]:
        seen: Set[int] = set()
        chars: Set[int] = set()
        hit = False
        stack = list(raw)
        while stack:
            s = stack.pop()
            if s in seen:
                continue
            seen.add(s)
            if s == accept_id:
                hit = True
                continue
            if nfa.chars[s] is not None:
                chars.add(s)
                continue
            a = nfa.asserts[s]
            if a == A_BOT and not at_start:
                continue
            if a == A_EOT and not at_end:
                continue
            stack.extend(nfa.eps[s])
        return frozenset(chars), hit

    det = _Determinizer(len(reps), budget)
    start_key = (frozenset((nfa_start,)), True)
    start, _ = det.intern(start_key)
    _, acc0 = closure(start_key[0], True, True)
    det.accept[start] = acc0
    work = [(start, start_key)]
    while work:
        sid, (raw, at_start) = work.pop()
        chars, hit_mid = closure(raw, at_start, False)
        if hit_mid:
            # search already succeeded before this position: sticky
            det.trans[sid] = [det.top()] * det.n_classes
            det.accept[sid] = True
            continue
        for c, rb in enumerate(reps):
            moved: Set[int] = set()
            for s in chars:
                if rb in byteset[s]:
                    moved.update(nfa.eps[s])
            # unanchored search: re-seed the NFA start at the next byte
            nraw = frozenset(moved | {nfa_start})
            nkey = (nraw, False)
            nid, fresh = det.intern(nkey)
            det.trans[sid][c] = nid
            if fresh:
                _, acc = closure(nraw, False, True)
                det.accept[nid] = acc
                work.append((nid, nkey))
    return Dfa(
        pattern=pattern, kind="re2",
        trans=np.asarray(det.trans, dtype=np.int32).reshape(
            len(det.trans), det.n_classes),
        class_map=class_map,
        accept=np.asarray(det.accept, dtype=bool),
        start=start, exact=det.exact,
        confirm_nonascii=True,
    )


# ---------------------------------------------------------------------------
# the bank: one packed table set per compiled policy set

@dataclass
class DfaBank:
    """All of a policy set's patterns, concatenated for one-dispatch
    evaluation. ``families`` records which byte-lane family each
    pattern is matched against (pool / name / ns / labels_kb /
    labels_vb), so the evaluator runs one scan per family covering
    every pattern used on it."""

    budget: int = field(default_factory=state_budget)
    patterns: List[Dfa] = field(default_factory=list)
    glob_ids: Dict[str, int] = field(default_factory=dict)
    re2_ids: Dict[str, int] = field(default_factory=dict)
    families: Dict[str, List[int]] = field(default_factory=dict)
    # packed (finalize())
    trans: Optional[np.ndarray] = None       # (S_total, C_max) uint16, GLOBAL ids
    class_map: Optional[np.ndarray] = None   # (P, 256) uint8
    start: Optional[np.ndarray] = None       # (P,) int32 global
    accept: Optional[np.ndarray] = None      # (S_total,) bool
    exact: Optional[np.ndarray] = None       # (P,) bool
    confirm_nonascii: Optional[np.ndarray] = None  # (P,) bool

    def _room(self, dfa: Dfa) -> bool:
        total = sum(p.n_states for p in self.patterns)
        return total + dfa.n_states <= MAX_BANK_STATES

    def add_glob(self, pattern: str, family: str) -> Optional[int]:
        """Register a glob; None when the bank is full (the evaluator
        then falls back to the legacy per-pattern NFA for it)."""
        pid = self.glob_ids.get(pattern)
        if pid is None:
            dfa = compile_glob(pattern, self.budget)
            if not self._room(dfa):
                return None
            pid = len(self.patterns)
            self.patterns.append(dfa)
            self.glob_ids[pattern] = pid
        self._note(family, pid)
        return pid

    def add_re2(self, pattern: str, family: str = "pool") -> int:
        """Register a regex; raises DfaUnsupported when non-lowerable
        or the bank has no room (the rule keeps its host route)."""
        pid = self.re2_ids.get(pattern)
        if pid is None:
            dfa = compile_re2(pattern, self.budget)
            if not self._room(dfa):
                raise DfaUnsupported("DFA bank state capacity exhausted")
            pid = len(self.patterns)
            self.patterns.append(dfa)
            self.re2_ids[pattern] = pid
        self._note(family, pid)
        return pid

    def _note(self, family: str, pid: int) -> None:
        ids = self.families.setdefault(family, [])
        if pid not in ids:
            ids.append(pid)
            ids.sort()

    def __len__(self) -> int:
        return len(self.patterns)

    def finalize(self) -> "DfaBank":
        P = len(self.patterns)
        c_max = max((p.n_classes for p in self.patterns), default=1)
        s_total = sum(p.n_states for p in self.patterns)
        trans = np.zeros((max(s_total, 1), c_max), dtype=np.uint16)
        cmap = np.zeros((max(P, 1), 256), dtype=np.uint8)
        start = np.zeros((max(P, 1),), dtype=np.int32)
        accept = np.zeros((max(s_total, 1),), dtype=bool)
        exact = np.ones((max(P, 1),), dtype=bool)
        conf_na = np.zeros((max(P, 1),), dtype=bool)
        base = 0
        for i, p in enumerate(self.patterns):
            n = p.n_states
            # pad columns repeat the state's class-0 move: class ids
            # beyond the pattern's own alphabet are never produced by
            # its class_map, so the padding is unreachable by design
            local = p.trans + base
            trans[base:base + n, :p.n_classes] = local
            if p.n_classes < c_max:
                trans[base:base + n, p.n_classes:] = local[:, :1]
            cmap[i] = p.class_map
            start[i] = base + p.start
            accept[base:base + n] = p.accept
            exact[i] = p.exact
            conf_na[i] = p.confirm_nonascii
            base += n
        self.trans, self.class_map = trans, cmap
        self.start, self.accept = start, accept
        self.exact, self.confirm_nonascii = exact, conf_na
        return self

    # -- introspection / identity

    def stats(self) -> Dict[str, int]:
        states = sum(p.n_states for p in self.patterns)
        packed = 0
        if self.trans is not None and self.patterns:
            # pattern-free banks hold 1-row placeholder arrays only —
            # report 0, not the placeholder footprint
            packed = (self.trans.nbytes + self.class_map.nbytes
                      + self.start.nbytes + self.accept.nbytes)
        return {"tables": len(self.patterns), "states": states,
                "bytes": packed,
                "approx": sum(1 for p in self.patterns if not p.exact)}

    def digest(self) -> str:
        """Cache-key material: the state budget changes table shapes
        (and the confirm ladder) without changing policy content, so
        the compiled-set identity must cover it."""
        h = hashlib.sha256()
        h.update(str(self.budget).encode())
        for p in self.patterns:
            h.update(f"|{p.kind}:{p.pattern}:{int(p.exact)}:"
                     f"{p.n_states}".encode())
        return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# batched device kernel: ONE scan over bytes steps every
# (pattern x string-lane) pair through the packed tables

def bank_match(bank: DfaBank, ids: Sequence[int], bytes_, lens):
    """Evaluate the bank patterns ``ids`` against padded byte tensors.

    bytes_: (..., W) uint8, lens: (...) int32 -> (..., K) bool accepts,
    K = len(ids). The scan performs two gathers per byte position —
    class lookup and transition lookup — for ALL pattern/string pairs
    at once; pad bytes beyond each string's length freeze the state, so
    acceptance reads out at exactly end-of-string."""
    import jax
    import jax.numpy as jnp

    assert bank.trans is not None, "bank not finalized"
    idx = np.asarray(list(ids), dtype=np.int32)
    K = idx.shape[0]
    cmap_t = jnp.asarray(bank.class_map[idx].T.astype(np.int32))  # (256, K)
    start = jnp.asarray(bank.start[idx])
    C = bank.trans.shape[1]
    trans_flat = jnp.asarray(bank.trans.reshape(-1).astype(np.int32))
    accept = jnp.asarray(bank.accept)
    lead = bytes_.shape[:-1]
    W = bytes_.shape[-1]
    state0 = jnp.broadcast_to(start, lead + (K,)).astype(jnp.int32)
    seq = jnp.moveaxis(bytes_, -1, 0)  # (W, ...)

    def step(state, xw):
        b, w = xw
        cls = cmap_t[b.astype(jnp.int32)]          # (..., K)
        nxt = jnp.take(trans_flat, state * C + cls)
        active = (w < lens)[..., None]
        return jnp.where(active, nxt, state), None

    state, _ = jax.lax.scan(
        step, state0, (seq, jnp.arange(W, dtype=np.int32)))
    return jnp.take(accept, state)


def nonascii_mask(bytes_, lens):
    """(...,) bool: any byte >= 0x80 within the string length — the
    subjects whose byte/codepoint semantics can diverge (they take the
    oracle-confirmation path for confirm_nonascii patterns)."""
    import jax.numpy as jnp

    W = bytes_.shape[-1]
    live = jnp.arange(W, dtype=np.int32) < lens[..., None]
    return ((bytes_ >= np.uint8(0x80)) & live).any(axis=-1)
