"""Pattern classes -> dense multi-stride DFA transition tables.

The device engine historically evaluated glob operands with a
bit-parallel NFA unrolled at trace time: one ``lax.scan`` with
O(pattern_len) boolean state columns PER DISTINCT PATTERN
(evaluator.glob_match). That shape is linear in patterns twice — XLA
program size and device work both grow with (patterns x positions) —
and regex patterns (CEL ``matches()``) had no device story at all,
keeping whole rules on the host path.

This module compiles the pattern classes the engine already parses —
``utils/wildcard`` globs, the tractable subset of ``cel/re2.py``
regexes — into dense DFA transition tables stepped as batched table
lookups (the Hyperflex SIMD-DFA model, arXiv:2512.07123): one
``(states x alphabet)`` uint16 table per pattern, alphabet compressed
to per-pattern byte classes, all tables of a policy set concatenated
into ONE bank evaluated in ONE ``lax.scan`` over the byte lanes —
every (pattern x string-lane) pair in a single fused dispatch.

Two composable compressions make the path hardware-shaped:

Multi-stride tables (Hyperflex): strided patterns share one FUSED
pad-extended group-pair table. The admitted patterns' byte-class maps
are jointly refined into Cg GROUP classes plus one PAD class (class id
Cg, representing "past end-of-string"); each pattern contributes a
``(S, (Cg+1)^2)`` two-step table built by composing its one-step table
with itself (``step1[step1]``), where the pad column is the identity —
so a (real, pad) column performs exactly the one trailing stride-1
move (the tail epilogue, folded into the table) and (pad, pad) freezes
the state. Table values are premultiplied by the pair pitch, so the
scan body is gather+add only: stride 2 runs ceil(W/2) steps of ONE
gather, stride 4 runs ceil(W/4) steps of TWO chained gathers — no
active mask, no length test, no epilogue in the scan at all. The
per-DFA stride is chosen by a table-growth budget
(``stride_table_entries`` per pattern, ``MAX_BANK_STRIDE_ENTRIES`` per
bank): stride 4 costs half the scan steps of stride 2 on the SAME
table, so it is preferred whenever the table fits half the per-pattern
cap. Stride composition is exact (T_2 = T_1 o T_1, chaining = T_4), so
every stride accepts the identical language.

Approximate reduction (arXiv:1710.08647): a DFA whose exact subset
construction exceeds the state budget is no longer bluntly collapsed.
The exact automaton is explored up to a larger cap, then reduced by a
k-lookahead language-equivalence heuristic: Moore partition refinement
stopped at the budgeted block count (states indistinguishable on all
suffixes of length <= k share a block), quotiented existentially and
re-determinized. The quotient of ANY partition over-approximates the
exact language, so a device MISS stays definitive. When refinement
reaches its fixpoint within budget the quotient IS the minimal DFA —
language-equal, the pattern stays ``exact`` and pays no confirmation
at all. Otherwise the over-approximation error (sampled acceptance
delta against the exact automaton over the class alphabet) is
measured; past the configured ceiling the pattern falls back to the
legacy accept-all TOP-collapse (counted on
``kyverno_dfa_top_collapse_total{reason}``). Containment
L(exact) subset-of L(approx) is additionally PROVEN by a product-state
BFS (``prove_miss_definitive``) under ``KYVERNO_TPU_SANITIZE=1``.

Exactness ladder:

- A pattern with a non-exact (over-approximating) DFA confirms device
  HITs on the scalar oracle — approximation costs confirmation work on
  the rare hits, never correctness.
- Tables run over UTF-8 BYTES while the host oracles match CODEPOINTS.
  For pure-ASCII subjects the two are identical; patterns whose
  semantics can differ on multi-byte subjects (``?`` globs — one char
  vs one byte — and every regex) carry ``confirm_nonascii``: subjects
  containing a byte >= 0x80 route to oracle confirmation regardless of
  the DFA verdict. ``*``-only ASCII-literal globs are byte-exact for
  ALL subjects and skip the ladder entirely.

Genuinely non-lowerable patterns (word boundaries, multiline anchors,
lookaround — which cel/re2.py itself rejects) raise
:class:`DfaUnsupported` and keep today's host route.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..cel.re2 import (
    A_BOT,
    A_EOT,
    Re2Error,
    _NFA,
    _Parser,
    _compile as _re2_nfa_compile,
)

__all__ = [
    "Dfa", "DfaBank", "DfaUnsupported", "compile_glob", "compile_re2",
    "bank_match", "nonascii_mask", "state_budget", "max_stride",
    "approx_error_ceiling", "prove_miss_definitive",
]


class DfaUnsupported(Exception):
    """Pattern outside the lowerable subset -> host route."""


DEFAULT_STATE_BUDGET = 192
# total bank states must index as uint16 with headroom
MAX_BANK_STATES = 60000
# exact-exploration headroom over the state budget before giving up
# on reduction and falling back to budgeted TOP-collapse
_EXPLORE_MULT = 8
_EXPLORE_MIN = 256
_EXPLORE_MAX = 4096
DEFAULT_MAX_STRIDE = 4
DEFAULT_APPROX_ERROR = 0.02
# strided-table growth budget: a fused pattern's table is
# n_states x (group_classes+1)^2 int32 entries, so cap the per-pattern
# and whole-bank entry counts
DEFAULT_STRIDE_TABLE_ENTRIES = 1 << 19
MAX_BANK_STRIDE_ENTRIES = 8 << 20
# the fused bank carries TWO 512-entry pad-extended byte -> group
# maps (byte | pad flag in bit 8; hi map premultiplied); charge them
# to the bank cap as stride-independent overhead
_FUSED_PAIR_ENTRIES = 1 << 10
# error-sampling corpus (seeded, deterministic per pattern)
_ERR_SAMPLES = 512
# product-BFS pair cap for the sanitize-time containment proof
_PROOF_PAIR_CAP = 4_000_000


def state_budget() -> int:
    """Per-pattern DFA state budget (the approximate-reduction knob):
    exact subset construction up to this many states, reduced /
    over-approximated beyond it. serve --dfa-state-budget / env
    override."""
    try:
        return max(4, int(os.environ.get("KYVERNO_TPU_DFA_STATE_BUDGET",
                                         str(DEFAULT_STATE_BUDGET))))
    except ValueError:
        return DEFAULT_STATE_BUDGET


def max_stride() -> int:
    """Largest transition stride the bank may compile (1, 2 or 4).
    serve --dfa-stride / KYVERNO_TPU_DFA_STRIDE; values in between
    clamp down to the nearest supported stride."""
    try:
        v = int(os.environ.get("KYVERNO_TPU_DFA_STRIDE",
                               str(DEFAULT_MAX_STRIDE)))
    except ValueError:
        return DEFAULT_MAX_STRIDE
    return 4 if v >= 4 else (2 if v >= 2 else 1)


def approx_error_ceiling() -> float:
    """Maximum measured over-approximation error tolerated before a
    budget-blowing pattern falls back to TOP-collapse. 0 disables
    approximate reduction entirely (legacy collapse behavior).
    serve --dfa-approx-error / KYVERNO_TPU_DFA_APPROX_ERROR."""
    try:
        v = float(os.environ.get("KYVERNO_TPU_DFA_APPROX_ERROR",
                                 str(DEFAULT_APPROX_ERROR)))
    except ValueError:
        return DEFAULT_APPROX_ERROR
    return min(1.0, max(0.0, v))


def stride_table_entries() -> int:
    """Per-pattern strided-table entry budget (table growth knob)."""
    try:
        return max(256, int(os.environ.get(
            "KYVERNO_TPU_DFA_STRIDE_ENTRIES",
            str(DEFAULT_STRIDE_TABLE_ENTRIES))))
    except ValueError:
        return DEFAULT_STRIDE_TABLE_ENTRIES


def _note_top_collapse(reason: str) -> None:
    # compile-time signal for the silent-footgun: memoization means one
    # increment per distinct (pattern, budget, ceiling) per process
    try:
        from ..observability.metrics import global_registry
        global_registry.dfa_top_collapse.inc({"reason": reason})
    except Exception:
        pass


@dataclass
class Dfa:
    """One compiled pattern: dense transition table over byte classes.

    ``trans`` is (n_states, n_classes) int32 with LOCAL state ids;
    ``class_map`` maps each byte 0..255 to its column; ``accept`` marks
    accepting states (evaluated at end-of-string — the scan freezes the
    state once the cursor passes the string length).

    ``approx_method`` records how the table relates to the pattern's
    language: ``exact`` (subset construction fit), ``minimized``
    (Moore fixpoint quotient — language-equal, still exact),
    ``klookahead`` (budgeted-refinement quotient — over-approximating
    with ``approx_error`` measured against the exact automaton) or
    ``top_collapse`` (legacy accept-all overflow state)."""

    pattern: str
    kind: str                    # glob | re2
    trans: np.ndarray
    class_map: np.ndarray        # (256,) uint8
    accept: np.ndarray           # (n_states,) bool
    start: int
    exact: bool                  # False => over-approximating (hit -> confirm)
    confirm_nonascii: bool       # byte/codepoint semantics may differ
    approx_method: str = "exact"
    states_merged: int = 0       # exact states folded away by reduction
    approx_error: float = 0.0    # sampled acceptance delta vs exact
    _stride_memo: Dict[int, np.ndarray] = field(
        default_factory=dict, repr=False, compare=False)

    @property
    def n_states(self) -> int:
        return int(self.trans.shape[0])

    @property
    def n_classes(self) -> int:
        return int(self.trans.shape[1])

    def strided_table(self, k: int) -> np.ndarray:
        """(n_states, n_classes**k) int32 LOCAL-id table consuming k
        byte classes per step: T_2 = T_1 o T_1, T_4 = T_2 o T_2 —
        composition is exact, every stride accepts the same language.
        Column index is the base-n_classes big-endian fold of the
        class k-tuple."""
        if k == 1:
            return self.trans
        tab = self._stride_memo.get(k)
        if tab is None:
            t2 = self.trans[self.trans]          # (S, C, C)
            t2 = t2.reshape(self.n_states, -1)   # (S, C^2)
            if k == 2:
                tab = np.ascontiguousarray(t2)
            elif k == 4:
                t4 = t2[t2]                      # (S, C^2, C^2)
                tab = np.ascontiguousarray(t4.reshape(self.n_states, -1))
            else:
                raise ValueError(f"unsupported stride {k}")
            self._stride_memo[k] = tab
        return tab

    def match_bytes(self, data: bytes) -> bool:
        """Host-side table walk — the parity/fuzz oracle for the packed
        device kernel (identical table, identical stepping order)."""
        s = self.start
        trans, cmap = self.trans, self.class_map
        for b in data:
            s = int(trans[s, cmap[b]])
        return bool(self.accept[s])

    def match_bytes_strided(self, data: bytes, k: int) -> bool:
        """Host-side strided walk mirroring the device kernel's group
        order: whole k-byte groups on the strided table, then the tail
        on the stride-1 table. Referee for stride composition."""
        tab = self.strided_table(k)
        C = self.n_classes
        cmap = self.class_map
        s = self.start
        n = (len(data) // k) * k
        for g in range(0, n, k):
            idx = 0
            for j in range(k):
                idx = idx * C + int(cmap[data[g + j]])
            s = int(tab[s, idx])
        for b in data[n:]:
            s = int(self.trans[s, cmap[b]])
        return bool(self.accept[s])

    def match_str(self, text: str) -> bool:
        return self.match_bytes(text.encode("utf-8"))


# ---------------------------------------------------------------------------
# alphabet compression: partition bytes by membership signature

def _byte_classes(predicates: Sequence[FrozenSet[int]]
                  ) -> Tuple[np.ndarray, List[int]]:
    """Bytes indistinguishable by every predicate share a class.
    Returns (class_map (256,) uint8, representative byte per class)."""
    if not predicates:
        return np.zeros(256, dtype=np.uint8), [0]
    member = np.zeros((len(predicates), 256), dtype=bool)
    for i, pred in enumerate(predicates):
        for b in pred:
            member[i, b] = True
    # unique signature columns -> class ids
    _, inverse = np.unique(member.T, axis=0, return_inverse=True)
    class_map = inverse.astype(np.uint8)
    reps: List[int] = []
    seen: Dict[int, int] = {}
    for b in range(256):
        c = int(class_map[b])
        if c not in seen:
            seen[c] = b
    for c in range(int(class_map.max()) + 1):
        reps.append(seen[c])
    return class_map, reps


class _Determinizer:
    """Budgeted subset construction. Overflow states collapse into one
    accept-all TOP state (over-approximation: miss stays definitive)."""

    def __init__(self, n_classes: int, budget: int):
        self.n_classes = n_classes
        self.budget = budget
        self.ids: Dict[object, int] = {}
        self.trans: List[List[int]] = []
        self.accept: List[bool] = []
        self.exact = True
        self._top: Optional[int] = None

    def top(self) -> int:
        if self._top is None:
            self._top = len(self.trans)
            self.trans.append([self._top] * self.n_classes)
            self.accept.append(True)
        return self._top

    def intern(self, key) -> Tuple[int, bool]:
        """(state id, is_new). Over budget -> TOP, exact=False."""
        sid = self.ids.get(key)
        if sid is not None:
            return sid, False
        if len(self.trans) >= self.budget:
            self.exact = False
            return self.top(), False
        sid = len(self.trans)
        self.ids[key] = sid
        self.trans.append([0] * self.n_classes)
        self.accept.append(False)
        return sid, True


# ---------------------------------------------------------------------------
# approximate reduction: k-lookahead quotient with measured error

def _moore_partition(trans: np.ndarray, accept: np.ndarray,
                     max_blocks: int) -> Tuple[np.ndarray, bool]:
    """Moore partition refinement stopped at the block budget.

    Returns (block id per state, at_fixpoint). Each refinement round
    deepens the lookahead by one byte class: after r rounds two states
    share a block iff they agree on acceptance for every suffix of
    length <= r — the k-lookahead language-equivalence heuristic of
    the approximate-reduction literature. At the fixpoint the blocks
    are exactly Myhill-Nerode classes (quotient = minimal DFA)."""
    block = accept.astype(np.int64)
    nb = int(block.max()) + 1 if block.size else 1
    while True:
        sig = np.concatenate([block[:, None], block[trans]], axis=1)
        _, newblock = np.unique(sig, axis=0, return_inverse=True)
        newblock = newblock.astype(np.int64)
        nnew = int(newblock.max()) + 1
        if nnew == nb:
            return block, True
        if nnew > max_blocks:
            # refusing the refinement keeps blocks <= max_blocks;
            # coarser partition => larger (over-approximated) language
            return block, False
        block, nb = newblock, nnew


def _quotient_exact(trans: np.ndarray, accept: np.ndarray, start: int,
                    block: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Quotient by a FIXPOINT partition: all members of a block agree
    on target blocks, so a representative per block yields the minimal
    DFA — language-equal to the input."""
    nb = int(block.max()) + 1
    rep = np.zeros(nb, dtype=np.int64)
    seen = np.zeros(nb, dtype=bool)
    for s in range(block.shape[0]):
        b = int(block[s])
        if not seen[b]:
            seen[b] = True
            rep[b] = s
    qtrans = block[trans[rep]].astype(np.int32)
    qaccept = accept[rep].copy()
    return qtrans, qaccept, int(block[start])


def _quotient_determinize(trans: np.ndarray, accept: np.ndarray,
                          start: int, block: np.ndarray, budget: int
                          ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Existential quotient of a NON-fixpoint partition, re-determinized
    under the budget. The quotient NFA of any partition accepts a
    superset of the input language (every exact run maps to a valid
    block run), and budgeted determinization only ever TOP-collapses
    further — the result is over-approximating by construction."""
    nb = int(block.max()) + 1
    S, C = trans.shape
    members: List[np.ndarray] = [np.nonzero(block == b)[0]
                                 for b in range(nb)]
    baccept = np.zeros(nb, dtype=bool)
    np.logical_or.at(baccept, block, accept)
    btrans: List[List[FrozenSet[int]]] = [
        [frozenset(int(x) for x in np.unique(block[trans[members[b], c]]))
         for c in range(C)]
        for b in range(nb)]
    det = _Determinizer(C, budget)
    key0 = frozenset((int(block[start]),))
    sid0, _ = det.intern(key0)
    det.accept[sid0] = bool(any(baccept[b] for b in key0))
    work: List[Tuple[int, FrozenSet[int]]] = [(sid0, key0)]
    while work:
        sid, K = work.pop()
        for c in range(C):
            tgt: FrozenSet[int] = frozenset().union(
                *[btrans[b][c] for b in K]) if K else frozenset()
            nid, fresh = det.intern(tgt)
            det.trans[sid][c] = nid
            if fresh:
                det.accept[nid] = bool(any(baccept[b] for b in tgt))
                work.append((nid, tgt))
    qtrans = np.asarray(det.trans, dtype=np.int32).reshape(
        len(det.trans), C)
    qaccept = np.asarray(det.accept, dtype=bool)
    return qtrans, qaccept, sid0


def _accept_goals(trans: np.ndarray, accept: np.ndarray) -> np.ndarray:
    """Per-state class choice stepping along a shortest path toward an
    accepting state (arbitrary for states that cannot reach one).
    Bellman iteration with early exit — iteration count is the
    automaton's accept eccentricity, ~pattern length in practice."""
    S = trans.shape[0]
    inf = np.int64(1) << 30
    dist = np.where(accept, np.int64(0), inf)
    for _ in range(S):
        nd = np.minimum(dist, 1 + dist[trans].min(axis=1))
        if np.array_equal(nd, dist):
            break
        dist = nd
    return np.argmin(dist[trans], axis=1).astype(np.int64)


def _sampled_error(etrans: np.ndarray, eaccept: np.ndarray, estart: int,
                   atrans: np.ndarray, aaccept: np.ndarray, astart: int,
                   seed: int) -> float:
    """Measured over-approximation error: P(approx accepts | exact
    rejects) over a seeded corpus of class strings (classes ARE the
    alphabet — every class is realized by >= 1 byte). Both walks are
    vectorized; determinism comes from the derived seed.

    A third of the corpus is uniform random; a third is guided toward
    the EXACT automaton's accepts (near-accepts: truncated digests,
    typo'd names — budget-starved quotients over-merge precisely
    around the accept neighborhood); a third is guided toward the
    APPROXIMATION's accepts — the adversarial probe that surfaces
    whole over-accepted sublanguages (e.g. a quotient that merged its
    dead state into a counting chain and now accepts anything CARRYING
    a digest-shaped suffix). Each guided step follows a shortest path
    toward an accepting state with high probability and deviates
    uniformly otherwise. The adversarial third makes the measure an
    upper-bound-seeking estimate: it can only over-report error, which
    costs a TOP-collapse (performance), never correctness."""
    C = etrans.shape[1]
    rng = np.random.default_rng(seed)
    L = int(min(max(16, 2 * etrans.shape[0]), 96))
    n = _ERR_SAMPLES
    lens = rng.integers(0, L + 1, size=n)
    seqs = rng.integers(0, C, size=(n, L))
    mode = np.arange(n) % 3          # 0 uniform | 1 exact | 2 approx
    follow = rng.random(size=(n, L)) < 0.85
    goal_e = _accept_goals(etrans, eaccept)
    goal_a = _accept_goals(atrans, aaccept)
    se = np.full(n, estart, dtype=np.int64)
    sa = np.full(n, astart, dtype=np.int64)
    for j in range(L):
        cls = seqs[:, j]
        cls = np.where(follow[:, j] & (mode == 1), goal_e[se], cls)
        cls = np.where(follow[:, j] & (mode == 2), goal_a[sa], cls)
        live = j < lens
        se = np.where(live, etrans[se, cls], se)
        sa = np.where(live, atrans[sa, cls], sa)
    neg = ~eaccept[se]
    false_acc = aaccept[sa] & neg
    return float(false_acc.sum()) / float(max(1, neg.sum()))


def _containment(etrans: np.ndarray, eaccept: np.ndarray, estart: int,
                 atrans: np.ndarray, aaccept: np.ndarray, astart: int,
                 max_pairs: int = _PROOF_PAIR_CAP) -> bool:
    """PROOF (not a sample) that L(exact) is contained in L(approx):
    BFS over reachable (exact, approx) state pairs looking for a pair
    accepting in the exact automaton but not in the approximation.
    Both automata must share one class alphabet (same class_map)."""
    if etrans.shape[1] != atrans.shape[1]:
        raise ValueError("containment proof needs a shared class alphabet")
    Se, Sa = etrans.shape[0], atrans.shape[0]
    if Se * Sa > max_pairs:
        raise ValueError(f"product too large ({Se * Sa} pairs)")
    visited = np.zeros(Se * Sa, dtype=bool)
    frontier = np.asarray([estart * Sa + astart], dtype=np.int64)
    visited[frontier] = True
    while frontier.size:
        se, sa = np.divmod(frontier, Sa)
        if bool(np.any(eaccept[se] & ~aaccept[sa])):
            return False
        nxt = (etrans[se].astype(np.int64) * Sa + atrans[sa]).ravel()
        nxt = np.unique(nxt)
        fresh = nxt[~visited[nxt]]
        visited[fresh] = True
        frontier = fresh
    return True


def prove_miss_definitive(exact: "Dfa", approx: "Dfa") -> bool:
    """Property-style miss-definitive proof: True iff every string the
    exact automaton accepts is accepted by the (possibly approximated)
    automaton — i.e. a device MISS on ``approx`` implies an oracle
    MISS. Requires both Dfas to share a byte-class map (always the
    case for the same pattern compiled at different budgets: the class
    partition is budget-independent)."""
    if not np.array_equal(exact.class_map, approx.class_map):
        raise ValueError("class_map mismatch: not the same pattern alphabet")
    return _containment(exact.trans, exact.accept, exact.start,
                        approx.trans, approx.accept, approx.start)


def _sanitize_on() -> bool:
    return os.environ.get("KYVERNO_TPU_SANITIZE", "") not in ("", "0")


def _reduce(kind: str, pattern: str, trans: np.ndarray,
            accept: np.ndarray, start: int, budget: int, ceiling: float
            ) -> Optional[Tuple[np.ndarray, np.ndarray, int, str, int,
                                float, bool]]:
    """Shrink an exact-but-over-budget DFA. Returns (trans, accept,
    start, method, states_merged, error, exact) or None when only
    TOP-collapse remains (caller rebuilds at the budget)."""
    S = trans.shape[0]
    block, fixpoint = _moore_partition(trans, accept, budget)
    nb = int(block.max()) + 1
    if fixpoint and nb <= budget:
        qtrans, qaccept, qstart = _quotient_exact(trans, accept, start,
                                                  block)
        return (qtrans, qaccept, qstart, "minimized", S - nb, 0.0, True)
    if ceiling <= 0.0:
        return None
    qtrans, qaccept, qstart = _quotient_determinize(
        trans, accept, start, block, budget)
    seed = int.from_bytes(
        hashlib.sha256(f"{kind}|{pattern}|{budget}".encode()).digest()[:8],
        "little")
    err = _sampled_error(trans, accept, start, qtrans, qaccept, qstart,
                         seed)
    if err > ceiling:
        return None
    merged = S - qtrans.shape[0]
    if _sanitize_on() and S * qtrans.shape[0] <= _PROOF_PAIR_CAP:
        if not _containment(trans, accept, start, qtrans, qaccept, qstart):
            raise RuntimeError(
                f"approximate reduction broke miss-definitive for "
                f"{kind} pattern {pattern!r}")
    return (qtrans, qaccept, qstart, "klookahead", merged, err, False)


def _explore_cap(budget: int) -> int:
    return max(budget,
               min(max(_EXPLORE_MULT * budget, _EXPLORE_MIN), _EXPLORE_MAX))


def _finish(kind: str, pattern: str, build, class_map: np.ndarray,
            budget: int, ceiling: float, confirm_nonascii: bool) -> Dfa:
    """Shared compile tail: explore exactly past the budget, reduce if
    needed, fall back to legacy budgeted TOP-collapse.

    A NEGATIVE ceiling selects pure legacy behavior (collapse at the
    budget with no exploration, minimization or reduction) — the
    pre-reduction baseline bench legs compare against."""
    if ceiling < 0.0:
        det, start = build(budget)
        trans = np.asarray(det.trans, dtype=np.int32).reshape(
            len(det.trans), det.n_classes)
        return Dfa(pattern=pattern, kind=kind, trans=trans,
                   class_map=class_map,
                   accept=np.asarray(det.accept, dtype=bool), start=start,
                   exact=det.exact, confirm_nonascii=confirm_nonascii,
                   approx_method="exact" if det.exact else "top_collapse")
    det, start = build(_explore_cap(budget))
    trans = np.asarray(det.trans, dtype=np.int32).reshape(
        len(det.trans), det.n_classes)
    accept = np.asarray(det.accept, dtype=bool)
    if det.exact and trans.shape[0] <= budget:
        return Dfa(pattern=pattern, kind=kind, trans=trans,
                   class_map=class_map, accept=accept, start=start,
                   exact=True, confirm_nonascii=confirm_nonascii)
    if det.exact:
        red = _reduce(kind, pattern, trans, accept, start, budget, ceiling)
        if red is not None:
            rtrans, raccept, rstart, method, merged, err, rexact = red
            return Dfa(pattern=pattern, kind=kind, trans=rtrans,
                       class_map=class_map, accept=raccept, start=rstart,
                       exact=rexact, confirm_nonascii=confirm_nonascii,
                       approx_method=method, states_merged=merged,
                       approx_error=err)
        _note_top_collapse(
            "error_ceiling" if ceiling > 0.0 else "approx_disabled")
    else:
        _note_top_collapse("explore_overflow")
    det, start = build(budget)
    trans = np.asarray(det.trans, dtype=np.int32).reshape(
        len(det.trans), det.n_classes)
    return Dfa(pattern=pattern, kind=kind, trans=trans,
               class_map=class_map,
               accept=np.asarray(det.accept, dtype=bool), start=start,
               exact=det.exact, confirm_nonascii=confirm_nonascii,
               approx_method="exact" if det.exact else "top_collapse")


# ---------------------------------------------------------------------------
# glob -> DFA (anchored full match, go-wildcard semantics over bytes)

def _glob_elems(pattern: str) -> List[Tuple]:
    elems: List[Tuple] = []
    for ch in pattern:
        if ch == "*":
            if elems and elems[-1][0] == "star":
                continue
            elems.append(("star",))
        elif ch == "?":
            elems.append(("any",))
        else:
            for b in ch.encode("utf-8"):
                elems.append(("byte", b))
    return elems


# compiled-table memo: subset construction runs once per (pattern,
# budget, ceiling) per process, not once per policy-set compile — the
# IR lowering probes compile_re2 for lowerability and the bank compiles
# the same pattern again, and lifecycle compile-ahead / quarantine
# bisect recompile whole sets repeatedly. Dfa instances are
# read-only-by-convention and safely shared across banks (the strided
# tables they memoize are shared too — composed once per process).
_DFA_MEMO: Dict[Tuple[str, str, int, float], "Dfa"] = {}
_DFA_MEMO_CAP = 1024


def _memoized(kind: str, pattern: str, budget: int, ceiling: float,
              build) -> "Dfa":
    key = (kind, pattern, budget, ceiling)
    dfa = _DFA_MEMO.get(key)
    if dfa is None:
        dfa = build()
        if len(_DFA_MEMO) >= _DFA_MEMO_CAP:
            _DFA_MEMO.clear()
        _DFA_MEMO[key] = dfa
    return dfa


def compile_glob(pattern: str, budget: Optional[int] = None,
                 ceiling: Optional[float] = None) -> Dfa:
    budget = budget or state_budget()
    ceiling = approx_error_ceiling() if ceiling is None else ceiling
    return _memoized("glob", pattern, budget, ceiling,
                     lambda: _compile_glob(pattern, budget, ceiling))


def _compile_glob(pattern: str, budget: int, ceiling: float) -> Dfa:
    elems = _glob_elems(pattern)
    m = len(elems)

    def close(posns: Set[int]) -> FrozenSet[int]:
        out = set(posns)
        stack = list(posns)
        while stack:
            j = stack.pop()
            if j < m and elems[j][0] == "star" and j + 1 not in out:
                out.add(j + 1)
                stack.append(j + 1)
        return frozenset(out)

    lits = sorted({e[1] for e in elems if e[0] == "byte"})
    predicates = [frozenset((b,)) for b in lits]
    has_any = any(e[0] in ("any", "star") for e in elems)
    if has_any:
        predicates.append(frozenset(range(256)))
    class_map, reps = _byte_classes(predicates)

    def build(cap: int) -> Tuple[_Determinizer, int]:
        det = _Determinizer(len(reps), cap)
        start_set = close({0})
        start, _ = det.intern(start_set)
        det.accept[start] = m in start_set
        work = [(start, start_set)]
        while work:
            sid, S = work.pop()
            for c, rb in enumerate(reps):
                moved: Set[int] = set()
                for j in S:
                    if j >= m:
                        continue
                    k, *payload = elems[j]
                    if k == "byte":
                        if payload[0] == rb:
                            moved.add(j + 1)
                    elif k == "any":
                        moved.add(j + 1)
                    else:  # star: consumes any byte, stays (closure adds j+1)
                        moved.add(j)
                nset = close(moved)
                nid, fresh = det.intern(nset)
                det.trans[sid][c] = nid
                if fresh:
                    det.accept[nid] = m in nset
                    work.append((nid, nset))
        return det, start

    return _finish("glob", pattern, build, class_map, budget, ceiling,
                   confirm_nonascii=("?" in pattern))


# ---------------------------------------------------------------------------
# re2 subset -> DFA (unanchored search, cel matches() semantics)

def _charset_bytes(cs) -> FrozenSet[int]:
    """ASCII bytes the charset matches exactly, plus the 0x80-0xFF lump
    whenever the set can match any non-ASCII codepoint (subjects with
    such bytes confirm on the oracle anyway — see module docstring)."""
    out = {b for b in range(128) if cs.matches(chr(b))}
    if cs.ci:
        high = True  # case folds can cross the ASCII boundary
    elif cs.negated:
        # negation matches some codepoint >= 128 unless the ranges
        # cover [128, 0x10FFFF] completely
        cursor = 128
        for lo, hi in sorted(cs.ranges):
            if hi < cursor:
                continue
            if lo > cursor:
                break
            cursor = hi + 1
        high = cursor <= 0x10FFFF
    else:
        high = any(hi >= 128 for _, hi in cs.ranges)
    if high:
        out |= set(range(128, 256))
    return frozenset(out)


def compile_re2(pattern: str, budget: Optional[int] = None,
                ceiling: Optional[float] = None) -> Dfa:
    """Compile a cel/re2.py pattern into a search DFA (partial-match
    semantics: the byte automaton re-seeds the NFA start at every
    position, acceptance is sticky). Raises DfaUnsupported for
    constructs byte tables cannot carry (word boundaries, multiline
    anchors) — and Re2Error propagates for non-RE2 syntax."""
    budget = budget or state_budget()
    ceiling = approx_error_ceiling() if ceiling is None else ceiling
    return _memoized("re2", pattern, budget, ceiling,
                     lambda: _compile_re2(pattern, budget, ceiling))


def _compile_re2(pattern: str, budget: int, ceiling: float) -> Dfa:
    try:
        ast = _Parser(pattern).parse()
    except Re2Error:
        raise
    nfa = _NFA()
    accept_id = nfa.state()
    nfa_start = _re2_nfa_compile(nfa, ast, accept_id)
    for a in nfa.asserts:
        if a is not None and a not in (A_BOT, A_EOT):
            raise DfaUnsupported(
                f"assertion {a} (word boundary / multiline anchor) has no "
                f"byte-DFA lowering")

    char_states = [s for s in range(len(nfa.chars))
                   if nfa.chars[s] is not None]
    byteset: Dict[int, FrozenSet[int]] = {
        s: _charset_bytes(nfa.chars[s]) for s in char_states}
    class_map, reps = _byte_classes(list(byteset.values()))

    def closure(raw: FrozenSet[int], at_start: bool, at_end: bool
                ) -> Tuple[FrozenSet[int], bool]:
        seen: Set[int] = set()
        chars: Set[int] = set()
        hit = False
        stack = list(raw)
        while stack:
            s = stack.pop()
            if s in seen:
                continue
            seen.add(s)
            if s == accept_id:
                hit = True
                continue
            if nfa.chars[s] is not None:
                chars.add(s)
                continue
            a = nfa.asserts[s]
            if a == A_BOT and not at_start:
                continue
            if a == A_EOT and not at_end:
                continue
            stack.extend(nfa.eps[s])
        return frozenset(chars), hit

    def build(cap: int) -> Tuple[_Determinizer, int]:
        det = _Determinizer(len(reps), cap)
        start_key = (frozenset((nfa_start,)), True)
        start, _ = det.intern(start_key)
        _, acc0 = closure(start_key[0], True, True)
        det.accept[start] = acc0
        work = [(start, start_key)]
        while work:
            sid, (raw, at_start) = work.pop()
            chars, hit_mid = closure(raw, at_start, False)
            if hit_mid:
                # search already succeeded before this position: sticky
                det.trans[sid] = [det.top()] * det.n_classes
                det.accept[sid] = True
                continue
            for c, rb in enumerate(reps):
                moved: Set[int] = set()
                for s in chars:
                    if rb in byteset[s]:
                        moved.update(nfa.eps[s])
                # unanchored search: re-seed the NFA start at the next byte
                nraw = frozenset(moved | {nfa_start})
                nkey = (nraw, False)
                nid, fresh = det.intern(nkey)
                det.trans[sid][c] = nid
                if fresh:
                    _, acc = closure(nraw, False, True)
                    det.accept[nid] = acc
                    work.append((nid, nkey))
        return det, start

    return _finish("re2", pattern, build, class_map, budget, ceiling,
                   confirm_nonascii=True)


# ---------------------------------------------------------------------------
# the bank: one packed table set per compiled policy set

@dataclass
class DfaBank:
    """All of a policy set's patterns, concatenated for one-dispatch
    evaluation. ``families`` records which byte-lane family each
    pattern is matched against (pool / name / ns / labels_kb /
    labels_vb), so the evaluator runs one scan per family covering
    every pattern used on it. ``owners`` tracks which policy/rule
    registered each pattern (for /debug/rules attribution)."""

    budget: int = field(default_factory=state_budget)
    ceiling: float = field(default_factory=approx_error_ceiling)
    patterns: List[Dfa] = field(default_factory=list)
    glob_ids: Dict[str, int] = field(default_factory=dict)
    re2_ids: Dict[str, int] = field(default_factory=dict)
    families: Dict[str, List[int]] = field(default_factory=dict)
    owners: Dict[int, List[str]] = field(default_factory=dict)
    # packed (finalize())
    trans: Optional[np.ndarray] = None       # (S_total, C_max) uint16, GLOBAL ids
    class_map: Optional[np.ndarray] = None   # (P, 256) uint8
    start: Optional[np.ndarray] = None       # (P,) int32 global
    accept: Optional[np.ndarray] = None      # (S_total,) bool
    exact: Optional[np.ndarray] = None       # (P,) bool
    confirm_nonascii: Optional[np.ndarray] = None  # (P,) bool
    # multi-stride packing (finalize()) — the FUSED pad-extended
    # group-pair tables shared by every stride>1 pattern
    strides: Optional[np.ndarray] = None     # (P,) int32 chosen stride
    fused_trans: Optional[np.ndarray] = None  # (S_fused*GP,) int32 premul
    fused_accept: Optional[np.ndarray] = None  # (S_fused,) bool
    fused_start: Optional[np.ndarray] = None  # (P,) int32 premul fused ids
    fused_pairs: Optional[np.ndarray] = None  # (1024,) int32 hi|lo maps
    fused_pitch: int = 0                      # (Cg+1)^2 row pitch

    def _room(self, dfa: Dfa) -> bool:
        total = sum(p.n_states for p in self.patterns)
        return total + dfa.n_states <= MAX_BANK_STATES

    def add_glob(self, pattern: str, family: str,
                 owner: Optional[str] = None) -> Optional[int]:
        """Register a glob; None when the bank is full (the evaluator
        then falls back to the legacy per-pattern NFA for it)."""
        pid = self.glob_ids.get(pattern)
        if pid is None:
            dfa = compile_glob(pattern, self.budget, self.ceiling)
            if not self._room(dfa):
                return None
            pid = len(self.patterns)
            self.patterns.append(dfa)
            self.glob_ids[pattern] = pid
        self._note(family, pid)
        self._own(pid, owner)
        return pid

    def add_re2(self, pattern: str, family: str = "pool",
                owner: Optional[str] = None) -> int:
        """Register a regex; raises DfaUnsupported when non-lowerable
        or the bank has no room (the rule keeps its host route)."""
        pid = self.re2_ids.get(pattern)
        if pid is None:
            dfa = compile_re2(pattern, self.budget, self.ceiling)
            if not self._room(dfa):
                raise DfaUnsupported("DFA bank state capacity exhausted")
            pid = len(self.patterns)
            self.patterns.append(dfa)
            self.re2_ids[pattern] = pid
        self._note(family, pid)
        self._own(pid, owner)
        return pid

    def _note(self, family: str, pid: int) -> None:
        ids = self.families.setdefault(family, [])
        if pid not in ids:
            ids.append(pid)
            ids.sort()

    def _own(self, pid: int, owner: Optional[str]) -> None:
        if owner is None:
            return
        names = self.owners.setdefault(pid, [])
        if owner not in names:
            names.append(owner)

    def __len__(self) -> int:
        return len(self.patterns)

    def finalize(self, stride: Optional[int] = None,
                 stride_entries: Optional[int] = None) -> "DfaBank":
        P = len(self.patterns)
        c_max = max((p.n_classes for p in self.patterns), default=1)
        s_total = sum(p.n_states for p in self.patterns)
        trans = np.zeros((max(s_total, 1), c_max), dtype=np.uint16)
        cmap = np.zeros((max(P, 1), 256), dtype=np.uint8)
        start = np.zeros((max(P, 1),), dtype=np.int32)
        accept = np.zeros((max(s_total, 1),), dtype=bool)
        exact = np.ones((max(P, 1),), dtype=bool)
        conf_na = np.zeros((max(P, 1),), dtype=bool)
        base = 0
        for i, p in enumerate(self.patterns):
            n = p.n_states
            # pad columns repeat the state's class-0 move: class ids
            # beyond the pattern's own alphabet are never produced by
            # its class_map, so the padding is unreachable by design
            local = p.trans + base
            trans[base:base + n, :p.n_classes] = local
            if p.n_classes < c_max:
                trans[base:base + n, p.n_classes:] = local[:, :1]
            cmap[i] = p.class_map
            start[i] = base + p.start
            accept[base:base + n] = p.accept
            exact[i] = p.exact
            conf_na[i] = p.confirm_nonascii
            base += n
        self.trans, self.class_map = trans, cmap
        self.start, self.accept = start, accept
        self.exact, self.confirm_nonascii = exact, conf_na

        # per-pattern stride selection under the table-growth budget.
        # All admitted patterns share ONE fused table family: the joint
        # group-class alphabet (plus the pad class) fixes the row pitch
        # GP = (Cg+1)^2, and a pattern's table costs n_states * GP
        # entries. Strides 2 and 4 use the SAME two-step table — stride
        # 4 chains two lookups per scan step, so the total gather count
        # is identical (W/2) and the deeper stride is strictly better
        # (half the sequential scan steps); admission is therefore a
        # pure table-size question and every admitted pattern runs at
        # the configured maximum stride.
        ms = max_stride() if stride is None else (
            4 if stride >= 4 else (2 if stride >= 2 else 1))
        per_cap = stride_table_entries() if stride_entries is None \
            else stride_entries
        strides = np.ones((max(P, 1),), dtype=np.int32)
        self.fused_trans = self.fused_accept = None
        self.fused_start = self.fused_pairs = None
        self.fused_pitch = 0
        admitted: List[int] = []
        if ms > 1 and P:
            # pass 1: admission against the pitch of the FULL candidate
            # set (conservative — the joint alphabet only shrinks when
            # patterns drop out)
            sigs = np.stack([p.class_map for p in self.patterns])
            uniq, _, _ = np.unique(sigs.T, axis=0, return_inverse=True,
                                   return_index=True)
            gp = (uniq.shape[0] + 1) ** 2
            total_entries = _FUSED_PAIR_ENTRIES
            for i, p in enumerate(self.patterns):
                e = p.n_states * gp
                if e > per_cap:
                    continue
                if total_entries + e > MAX_BANK_STRIDE_ENTRIES:
                    continue
                total_entries += e
                strides[i] = ms
                admitted.append(i)
        if admitted:
            # pass 2: joint byte-class refinement over the admitted
            # patterns; rep_idx picks one representative byte per group
            # class for translating each pattern's own class columns
            sigs = np.stack([self.patterns[i].class_map
                             for i in admitted])
            uniq, rep_idx, gcmap = np.unique(
                sigs.T, axis=0, return_index=True, return_inverse=True)
            cg = int(uniq.shape[0])
            gb = cg + 1          # + the pad class
            gp = gb * gb
            s_f = sum(self.patterns[i].n_states for i in admitted)
            ftab = np.zeros((s_f, gp), dtype=np.int64)
            facc = np.zeros((s_f,), dtype=bool)
            fstart = np.zeros((max(P, 1),), dtype=np.int64)
            fb = 0
            for i in admitted:
                p = self.patterns[i]
                n = p.n_states
                cm = p.class_map[rep_idx].astype(np.int64)
                # one-step table over group classes; the pad column is
                # the identity, so composing the table with itself
                # yields the two-step table WITH the tail epilogue
                # folded in: (real, pad) = one stride-1 move,
                # (pad, pad) = freeze
                step1 = np.concatenate(
                    [p.trans.astype(np.int64)[:, cm],
                     np.arange(n, dtype=np.int64)[:, None]], axis=1)
                ftab[fb:fb + n] = (step1[step1] + fb).reshape(n, gp)
                facc[fb:fb + n] = p.accept
                fstart[i] = fb + p.start
                fb += n
            # premultiply every stored id by the pitch: the scan body
            # becomes gather+add only (state already carries the row
            # offset), final states divide the pitch back out
            gx = np.concatenate([gcmap.astype(np.int64),
                                 np.full(256, cg, dtype=np.int64)])
            self.fused_trans = (ftab * gp).astype(np.int32).reshape(-1)
            self.fused_accept = facc
            self.fused_start = (fstart * gp).astype(np.int32)
            # two cache-resident 512-entry maps (hi premultiplied by
            # the group base) instead of one 512x512 product table: a
            # pair column is fused_pairs[b0] + fused_pairs[512 + b1]
            self.fused_pairs = np.concatenate(
                [gx * gb, gx]).astype(np.int32)
            self.fused_pitch = gp
        self.strides = strides
        return self

    # -- introspection / identity

    def stats(self) -> Dict[str, object]:
        states = sum(p.n_states for p in self.patterns)
        packed = 0
        stride_bytes = 0
        if self.trans is not None and self.patterns:
            # pattern-free banks hold 1-row placeholder arrays only —
            # report 0, not the placeholder footprint
            if self.fused_trans is not None:
                stride_bytes = (self.fused_trans.nbytes
                                + self.fused_pairs.nbytes
                                + self.fused_accept.nbytes)
            packed = (self.trans.nbytes + self.class_map.nbytes
                      + self.start.nbytes + self.accept.nbytes
                      + stride_bytes)
        hist: Dict[str, int] = {}
        if self.strides is not None and self.patterns:
            for k in self.strides[:len(self.patterns)]:
                hist[str(int(k))] = hist.get(str(int(k)), 0) + 1
        return {"tables": len(self.patterns), "states": states,
                "bytes": packed,
                "approx": sum(1 for p in self.patterns if not p.exact),
                "top_collapsed": sum(
                    1 for p in self.patterns
                    if p.approx_method == "top_collapse"),
                "states_merged": sum(p.states_merged
                                     for p in self.patterns),
                "max_approx_error": max(
                    (p.approx_error for p in self.patterns), default=0.0),
                "stride_hist": hist, "stride_bytes": stride_bytes}

    def pattern_report(self) -> List[Dict[str, object]]:
        """Per-pattern compile status for /debug/rules: which rules pay
        CONFIRM trips (approximated / TOP-collapsed patterns) and which
        stride each pattern runs at."""
        out: List[Dict[str, object]] = []
        for i, p in enumerate(self.patterns):
            if p.approx_method == "top_collapse":
                status = "top_collapse"
            elif not p.exact:
                status = "approximated"
            elif p.states_merged:
                status = "minimized"
            else:
                status = "exact"
            out.append({
                "pattern": p.pattern[:120], "kind": p.kind,
                "status": status,
                "stride": int(self.strides[i])
                if self.strides is not None else 1,
                "states": p.n_states,
                "states_merged": p.states_merged,
                "approx_error": round(float(p.approx_error), 6),
                "confirm_on_hit": not p.exact,
                "confirm_nonascii": p.confirm_nonascii,
                "families": sorted(f for f, ids in self.families.items()
                                   if i in ids),
                "rules": list(self.owners.get(i, [])),
            })
        return out

    def digest(self) -> str:
        """Cache-key material: the state budget, error ceiling and
        chosen strides change table shapes (and the confirm ladder)
        without changing policy content, so the compiled-set identity
        must cover them."""
        h = hashlib.sha256()
        h.update(f"{self.budget}:{self.ceiling}".encode())
        for i, p in enumerate(self.patterns):
            k = int(self.strides[i]) if self.strides is not None else 0
            h.update(f"|{p.kind}:{p.pattern}:{int(p.exact)}:"
                     f"{p.n_states}:{p.approx_method}:"
                     f"{p.states_merged}:{k}".encode())
        return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# batched device kernel: ONE scan per stride group steps every
# (pattern x string-lane) pair through the packed tables

def _scan_stride1(bank: DfaBank, idx: np.ndarray, bytes_, lens):
    """Final states after the classic one-byte-per-step scan."""
    import jax
    import jax.numpy as jnp

    K = idx.shape[0]
    cmap_t = jnp.asarray(bank.class_map[idx].T.astype(np.int32))  # (256, K)
    start = jnp.asarray(bank.start[idx])
    C = bank.trans.shape[1]
    trans_flat = jnp.asarray(bank.trans.reshape(-1).astype(np.int32))
    lead = bytes_.shape[:-1]
    W = bytes_.shape[-1]
    state0 = jnp.broadcast_to(start, lead + (K,)).astype(jnp.int32)
    seq = jnp.moveaxis(bytes_, -1, 0)  # (W, ...)

    def step(state, xw):
        b, w = xw
        cls = cmap_t[b.astype(jnp.int32)]          # (..., K)
        nxt = jnp.take(trans_flat, state * C + cls)
        active = (w < lens)[..., None]
        return jnp.where(active, nxt, state), None

    state, _ = jax.lax.scan(
        step, state0, (seq, jnp.arange(W, dtype=np.int32)))
    return state


def _scan_fused(bank: DfaBank, idx: np.ndarray, bytes_, lens, chain: int):
    """Final FUSED-LOCAL states after the pad-extended strided scan.

    ``chain=1`` is stride 2 (one table lookup per step), ``chain=2`` is
    stride 4 (two chained lookups per step on the same table). The scan
    body is pure gather+add: each byte is extended with a pad flag
    (bit 8 set once the cursor passes the string length), two 512-entry
    cache-resident maps fold two extended bytes into a premultiplied
    group-pair column, and the table entry already carries the next row
    offset.
    Lengths — including lengths not a multiple of the stride — need no
    mask or epilogue: pad columns walk the identity."""
    import jax
    import jax.numpy as jnp

    K = idx.shape[0]
    gp = bank.fused_pitch
    ftab = jnp.asarray(bank.fused_trans)
    fpair = jnp.asarray(bank.fused_pairs)
    start = jnp.asarray(bank.fused_start[idx])

    lead = bytes_.shape[:-1]
    W = bytes_.shape[-1]
    npairs = -(-W // 2)
    G = -(-npairs // chain)
    wp = G * chain * 2
    bytes_p = bytes_
    if wp != W:
        # pad the window so the pair count divides the chain length;
        # the padding always classifies as (pad, pad) = freeze
        bytes_p = jnp.pad(
            bytes_, [(0, 0)] * (bytes_.ndim - 1) + [(0, wp - W)])
    lens_c = jnp.minimum(lens, W)  # packing truncated the bytes at W

    # classify in native (..., wp) layout — only the classified pair
    # stream (half the window) pays the scan-order transpose
    pos = jnp.arange(wp, dtype=np.int32)
    bx = (bytes_p.astype(jnp.int32)
          + (pos >= lens_c[..., None]).astype(jnp.int32) * 256)
    u = fpair[bx[..., 0::2]] + fpair[512 + bx[..., 1::2]]  # (..., wp/2)
    seq = jnp.moveaxis(u, -1, 0).reshape((G, chain) + lead)
    state0 = jnp.broadcast_to(start, lead + (K,)).astype(jnp.int32)

    def step(state, grp):
        s = state
        for j in range(chain):
            s = jnp.take(ftab, s + grp[j][..., None])
        return s, None

    state, _ = jax.lax.scan(step, state0, seq)
    return state // gp


def bank_match(bank: DfaBank, ids: Sequence[int], bytes_, lens):
    """Evaluate the bank patterns ``ids`` against padded byte tensors.

    bytes_: (..., W) uint8, lens: (...) int32 -> (..., K) bool accepts,
    K = len(ids). Patterns are partitioned by their compiled stride:
    each group runs one ``lax.scan`` of ceil(W/stride) steps. Strided
    groups run on the fused premultiplied pad-extended table — the
    scan body is gather+add only; per-string lengths are encoded as
    pad classes in the column stream, so the state freezes at exactly
    end-of-string with no mask or epilogue."""
    import jax.numpy as jnp

    assert bank.trans is not None, "bank not finalized"
    idx = np.asarray(list(ids), dtype=np.int32)
    accept = jnp.asarray(bank.accept)
    if bank.strides is None:
        return jnp.take(accept, _scan_stride1(bank, idx, bytes_, lens))
    strides = bank.strides[idx]
    order: List[np.ndarray] = []
    parts = []
    for k in sorted(set(int(s) for s in strides)):
        sel = np.nonzero(strides == k)[0]
        sub = idx[sel]
        if k == 1:
            parts.append(jnp.take(
                accept, _scan_stride1(bank, sub, bytes_, lens)))
        else:
            state = _scan_fused(bank, sub, bytes_, lens,
                                2 if k == 4 else 1)
            parts.append(jnp.take(jnp.asarray(bank.fused_accept), state))
        order.append(sel)
    if len(parts) == 1:
        return parts[0]
    full = jnp.concatenate(parts, axis=-1)
    inv = np.argsort(np.concatenate(order))
    return full[..., jnp.asarray(inv)]


def nonascii_mask(bytes_, lens):
    """(...,) bool: any byte >= 0x80 within the string length — the
    subjects whose byte/codepoint semantics can diverge (they take the
    oracle-confirmation path for confirm_nonascii patterns)."""
    import jax.numpy as jnp

    W = bytes_.shape[-1]
    live = jnp.arange(W, dtype=np.int32) < lens[..., None]
    return ((bytes_ >= np.uint8(0x80)) & live).any(axis=-1)
