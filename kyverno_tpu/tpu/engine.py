"""TpuEngine — batch policy evaluation over the device plane.

The scan-path equivalent of the reference's reports-controller hot loop
(pkg/controllers/report/background/controller.go:299 reconcileReport ->
engine.Validate per policy): encode the resource snapshot once, then
evaluate the full policy x resource cross-product as one device
program. Rules the IR compiler cannot lower (RuleEntry.fallback_reason)
and resources exceeding encode caps are completed with the scalar
engine, so results always cover everything.

Verdict codes follow evaluator.py: 0 PASS, 1 SKIP, 2 FAIL,
3 NOT_MATCHED, 4 ERROR (5 HOST and 6 CONFIRM never escape — both are
resolved here; CONFIRM is the pattern-confirmation sub-batch from the
approximate-DFA ladder, counted as device work in coverage terms
because only the rare hits pay it).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.policy import ClusterPolicy
from ..engine.context import Context
from ..engine.engine import Engine as ScalarEngine
from ..engine.match import RequestInfo
from ..engine.policycontext import PolicyContext
from ..engine.response import EngineResponse
from ..observability.analytics import (NUM_CLASSES, RuleIdent, class_counts,
                                       global_pattern_cells,
                                       global_rule_stats, global_starvation)
from ..observability.profiling import (PATH_DEVICE, PATH_SCALAR_FALLBACK,
                                       PHASE_DISPATCH, PHASE_ENCODE,
                                       PHASE_HOST_COMPLETE, PHASE_READBACK,
                                       global_profiler, maybe_xla_trace,
                                       set_dispatch_path)
from ..observability.tracing import global_tracer
from ..devtools import sanitizer as _sanitizer
from ..resilience.faults import (SITE_MUTATE_TRIAGE, SITE_TPU_DISPATCH,
                                 global_faults)
from .compiler import CompiledPolicySet, compile_policy_set
from .evaluator import (CONFIRM, ERROR, FAIL, HOST, NOT_MATCHED, PASS, SKIP,
                        batch_to_host)
from .flatten import EncodeConfig, encode_resources
from .metadata import MetaConfig, encode_metadata

VERDICT_NAMES = {PASS: "pass", SKIP: "skip", FAIL: "fail",
                 NOT_MATCHED: "not_matched", ERROR: "error"}


class DeviceResultError(RuntimeError):
    """The device program returned a wrong-shaped/typed verdict table —
    treated exactly like a dispatch failure (breaker + scalar fallback),
    never silently consumed as truth."""

_STATUS_TO_CODE = {"pass": PASS, "skip": SKIP, "fail": FAIL, "error": ERROR}


def _scan_json_context(resource: Dict[str, Any], operation: str = "",
                       admission_info: Optional[RequestInfo] = None) -> Context:
    """The JSON context both engines evaluate against for one scanned
    resource: request.object/namespace/operation/userInfo + images.*
    (policy_context.go:257)."""
    ctx = Context()
    ctx.add_resource(resource)
    ns = (resource.get("metadata") or {}).get("namespace", "")
    if ns:
        ctx.add_namespace(ns)
    if operation:
        ctx.add_operation(operation)
    info = admission_info or RequestInfo()
    ctx.add_user_info({"username": info.username, "uid": info.uid,
                       "groups": info.groups})
    try:
        from ..images import extract_images

        extracted = extract_images(resource)
        if extracted:
            ctx.add_image_infos({
                group: {key: info_.to_dict() for key, info_ in entries.items()}
                for group, entries in extracted.items()})
    except Exception:
        pass  # malformed image strings must not break context building
    return ctx


def build_scan_context(
    policy: ClusterPolicy,
    resource: Dict[str, Any],
    namespace_labels: Optional[Dict[str, str]],
    operation: str = "",
    admission_info: Optional[RequestInfo] = None,
) -> PolicyContext:
    """Background-scan PolicyContext: request.operation stays absent
    unless a real admission operation exists (the charts' preconditions
    rely on `request.operation || 'BACKGROUND'`). Match-gating still
    defaults to CREATE (MatchesResourceDescription's default)."""
    ctx = _scan_json_context(resource, operation, admission_info)
    info = admission_info or RequestInfo()
    return PolicyContext(
        policy=policy,
        new_resource=resource,
        admission_info=info,
        namespace_labels=namespace_labels or {},
        operation=operation or "CREATE",
        json_context=ctx,
    )


@dataclass
class ScanResult:
    """(num_rules_total, N) verdict table + rule metadata."""

    verdicts: np.ndarray
    rules: List[Tuple[str, str]]  # (policy_name, rule_name) per row

    def counts(self) -> Dict[str, int]:
        out = {name: int((self.verdicts == code).sum()) for code, name in VERDICT_NAMES.items()}
        return out

    def violations(self) -> List[Tuple[int, int]]:
        """(rule_row, resource_idx) pairs with FAIL verdicts."""
        rows, cols = np.nonzero(self.verdicts == FAIL)
        return list(zip(rows.tolist(), cols.tolist()))


@dataclass
class MutateTriageResult:
    """(num_mutate_rules, N) needs-mutation verdict table in compiled
    bank order. PASS/FAIL = rule applies; SKIP/NOT_MATCHED = it does
    not; ERROR/HOST/CONFIRM = undecidable on device, the coordinator
    routes the policy to the scalar patcher."""

    verdicts: np.ndarray
    rules: List[Tuple[str, str]]  # (policy_name, rule_name) per row

    def rows_for(self, ci: int) -> List[Tuple[Tuple[str, str], int]]:
        """One resource's bank-ordered ((policy, rule), code) rows —
        the coordinator's input shape."""
        return [(ident, int(self.verdicts[mi, ci]))
                for mi, ident in enumerate(self.rules)]

    def counts(self) -> Dict[str, int]:
        pos = int(((self.verdicts == PASS) | (self.verdicts == FAIL)).sum())
        neg = int(((self.verdicts == SKIP)
                   | (self.verdicts == NOT_MATCHED)).sum())
        return {"positive": pos, "negative": neg,
                "host": int(self.verdicts.size) - pos - neg}


def _scalar_rule_verdicts(
    engine: ScalarEngine, policy: ClusterPolicy, pctx: PolicyContext
) -> Dict[str, int]:
    """Run the scalar engine for one (policy, resource); map each
    validate rule to a verdict code (absent response = not matched)."""
    response: EngineResponse = engine.validate(pctx)
    got = {rr.name: _STATUS_TO_CODE.get(rr.status, ERROR) for rr in response.policy_response.rules}
    out: Dict[str, int] = {}
    for rule in policy.get_rules():
        if rule.has_validate():
            out[rule.name] = got.get(rule.name, NOT_MATCHED)
    return out


def _walk_values(node, segs, i=0):
    """Yield the values at a PathState segment chain over a raw
    resource dict (ARRAY_SEG iterates list elements)."""
    from .hashing import ARRAY_SEG

    if i == len(segs):
        yield node
        return
    seg = segs[i]
    if seg == ARRAY_SEG:
        if isinstance(node, list):
            for el in node:
                yield from _walk_values(el, segs, i + 1)
    elif isinstance(node, dict) and seg in node:
        yield from _walk_values(node[seg], segs, i + 1)


class TpuEngine:
    """Compile once, scan many — the device-backed engineapi.Engine
    slice for background scans and CLI apply."""

    def __init__(
        self,
        policies: Sequence[ClusterPolicy] = (),
        encode_cfg: Optional[EncodeConfig] = None,
        meta_cfg: Optional[MetaConfig] = None,
        cps: Optional[CompiledPolicySet] = None,
        exceptions: Sequence[Any] = (),
        data_sources=None,
        breaker=None,
    ):
        self.cps: CompiledPolicySet = cps if cps is not None \
            else compile_policy_set(policies, encode_cfg, meta_cfg, data_sources)
        self.data_sources = data_sources  # runtime dyn-operand loading
        # device errors are device-wide, so engines share the process
        # breaker by default (engines churn with policy revisions)
        if breaker is None:
            from ..resilience.breaker import tpu_breaker

            breaker = tpu_breaker()
        self.breaker = breaker
        self.scalar = ScalarEngine(exceptions=list(exceptions),
                                   background=True,
                                   data_sources=data_sources)
        # rules named by any PolicyException evaluate on the host: the
        # exception's match/conditions are per-resource dynamic state
        # the compiled program does not model (engine/exceptions.go)
        self._exception_rules: set = set()
        # same host routing for the mutate triage bank: an excepted
        # mutate rule's apply decision is per-resource dynamic state
        self._exception_mutate_rules: set = set()
        if exceptions:
            from ..api.exception import PolicyException

            typed = [e if isinstance(e, PolicyException)
                     else PolicyException.from_dict(e) for e in exceptions]
            for ri, entry in enumerate(self.cps.rules):
                if any(t.contains(entry.policy_name, entry.rule_name)
                       for t in typed):
                    self._exception_rules.add(ri)
            for mi, entry in enumerate(self.cps.mutate_entries):
                if any(t.contains(entry.policy_name, entry.rule_name)
                       for t in typed):
                    self._exception_mutate_rules.add(mi)
        # verdict-cache identity (tpu/cache.py): exceptions change
        # verdicts without changing the compiled set, so they join the
        # policy-set content key
        from .cache import digest as _digest

        self._exceptions_digest = _digest(
            [e if isinstance(e, dict) else getattr(e, "raw", None) or repr(e)
             for e in exceptions]) if exceptions else ""
        self._cache_ident: Optional[str] = None
        self._cache_eligible: Optional[bool] = None
        self._mutate_cache_eligible: Optional[bool] = None
        self._encode_cache_key: Optional[str] = None
        # encoder-pool profile for the rows feed, registered lazily per
        # pool instance (a reconfigured pool gets a fresh profile)
        self._pool_profile: Optional[Tuple[Any, int]] = None
        # policy observatory: per-rule analytics identities + the
        # thread-local slot the device-side verdict-count reduction
        # rides from dispatch to assemble (thread-local because one
        # engine may serve the flusher thread and a scan thread)
        self._rule_idents: Optional[List[RuleIdent]] = None
        self._tls = threading.local()
        try:
            global_rule_stats.register(self.rule_idents())
        except Exception:
            pass  # analytics must never block engine construction
        self.cps.publish_dfa_gauges()

    @classmethod
    def from_compiled(cls, cps: CompiledPolicySet) -> "TpuEngine":
        return cls(cps=cps)

    # -- encoding

    DYN_LIST_L = 32  # padded list-operand lanes per slot

    def encode(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
    ):
        rows = self._encode_rows(resources)
        meta = encode_metadata(resources, namespace_labels, operations,
                               admission_infos, self.cps.meta_cfg)
        batch = batch_to_host(rows, meta)
        if self.cps.dyn_slots:
            batch.update(self._encode_dyn_lanes(resources, operations,
                                                admission_infos))
        return batch, rows, meta

    def _encode_rows(self, resources: Sequence[Dict[str, Any]]):
        """Row encoding through the content-addressed encode cache: an
        unchanged resource's lane rows restore from the LRU instead of
        re-walking the JSON tree. Keyed by encode config + compiled
        byte-path sets, NOT policy content — a policy-set revision bump
        keeps every entry warm (the verdict cache misses, this one
        doesn't).

        With an encoder pool configured, cache MISSES encode on a
        worker process (the serving batcher's feed rides the same
        supervised ladder as the scan feed), and the pooled results
        populate the shared cache — warm rows never re-enter the pool.
        A pool bypass/infra failure falls back to in-process encode; a
        poison resource (crashes two workers, bisected) comes back
        flagged and is marked for host fallback exactly like an
        encode-cap overflow — the scalar oracle answers its column."""
        from ..cluster.columnar import get_store
        from .cache import (EncodeRowCache, apply_rows_multi, extract_rows,
                            global_encode_cache, resource_content_hash)
        from .flatten import RowBatch

        ec = global_encode_cache
        store = get_store()
        if not ec.enabled and store is None:
            return encode_resources(resources, self.cps.encode_cfg,
                                    self.cps.byte_paths,
                                    self.cps.key_byte_paths)
        if self._encode_cache_key is None:
            self._encode_cache_key = EncodeRowCache.encode_key(
                self.cps.encode_cfg, self.cps.byte_paths,
                self.cps.key_byte_paths)
        batch = RowBatch(len(resources), self.cps.encode_cfg)
        misses: List[Tuple[int, Optional[Tuple[str, str]]]] = []
        hit_entries: List[Any] = []
        hit_idx: List[int] = []
        for i, res in enumerate(resources):
            h = resource_content_hash(res)
            key = (self._encode_cache_key, h) if h is not None else None
            entry = (ec.get_entry(key)
                     if key is not None and ec.enabled else None)
            if entry is None and key is not None and store is not None:
                # columnar tier under the LRU: rows another engine (or
                # the scan loop, or a prior process via mmap) encoded
                entry = store.get_entry(self._encode_cache_key, h)
            if entry is None:
                misses.append((i, key))
            else:
                hit_entries.append(entry)
                hit_idx.append(i)
        # ALL hits land in one vectorized fancy-index scatter per lane
        # (apply_rows_multi) instead of a per-resource Python loop
        apply_rows_multi(hit_entries, batch, hit_idx)
        if misses and self._encode_rows_pooled(resources, batch, misses, ec):
            return batch
        if misses:
            sub = encode_resources([resources[i] for i, _ in misses],
                                   self.cps.encode_cfg, self.cps.byte_paths,
                                   self.cps.key_byte_paths)
            sub_arrays = sub.arrays()
            batch_arrays = batch.arrays()
            for j, (i, key) in enumerate(misses):
                for name, arr in sub_arrays.items():
                    batch_arrays[name][i] = arr[j]
                if key is not None:
                    entry = extract_rows(sub, j)
                    ec.put_entry(key, entry)
                    if store is not None:
                        store.put_entry(self.cps.encode_cfg,
                                        self.cps.byte_paths,
                                        self.cps.key_byte_paths,
                                        key[1], entry)
        return batch

    # pooling a miss set smaller than this costs more in IPC round-trip
    # than the in-process encode it replaces (the admission path is
    # latency-sensitive; a near-warm cache leaves 1-2 misses per flush)
    POOL_ROWS_MIN = 4

    def _encode_rows_pooled(self, resources, batch, misses, ec) -> bool:
        """Encode the cache misses on the encoder pool; True when the
        batch rows were filled (False -> caller encodes in-process)."""
        if len(misses) < self.POOL_ROWS_MIN:
            return False
        from ..encode import (KIND_ROWS, PoolBypassed, PoolInfraError,
                              WorkerEncodeError, get_pool, profile_spec)
        from .cache import apply_rows

        pool = get_pool()
        if pool is None or not pool.running:
            return False
        try:
            if (self._pool_profile is None
                    or self._pool_profile[0] is not pool):
                self._pool_profile = (pool, pool.register_profile(
                    profile_spec(self.cps.encode_cfg,
                                 byte_paths=self.cps.byte_paths,
                                 key_byte_paths=self.cps.key_byte_paths)))
            out = pool.encode_chunk(
                self._pool_profile[1], KIND_ROWS,
                {"resources": [resources[i] for i, _ in misses]})
        except (PoolBypassed, PoolInfraError, WorkerEncodeError):
            # breaker open / infra out -> in-process path; a worker-
            # REPORTED encode error re-raises in-process too, where the
            # existing quarantine ladder owns it
            return False
        from ..cluster.columnar import get_store

        store = get_store()
        poison = set(out.get("poison") or ())
        for j, (i, key) in enumerate(misses):
            if j in poison:
                # quarantined: empty lanes + the fallback flag route
                # this column to the scalar oracle (HOST), and its
                # placeholder rows never enter the cache
                batch.fallback[i] = 1
                continue
            entry = out["rows"][j]
            apply_rows(entry, batch, i)
            if key is not None:
                ec.put_entry(key, entry)
                if store is not None:
                    # pooled results are system-of-record rows too: the
                    # next scan gathers them instead of re-encoding
                    store.put_entry(self.cps.encode_cfg,
                                    self.cps.byte_paths,
                                    self.cps.key_byte_paths,
                                    key[1], entry)
        return True

    def _encode_dyn_lanes(self, resources, operations, admission_infos):
        """Host-resolved context operands (SURVEY §7 context-dependent
        rules): per (slot, resource), load the slot's context entries
        through the REAL loaders (apiCall/configMap I/O included,
        exactly the scalar engine's path) and encode the queried value
        as canonical lanes the device program compares against.
        Load results cache on the substituted entry spec, so
        request-independent entries (static urlPaths, configMaps)
        resolve once per batch."""
        S, N, L = len(self.cps.dyn_slots), len(resources), self.DYN_LIST_L
        lanes = {
            # type: 0=load-error 1=null 2=bool 3=num 4=str 5=list 6=other
            "dyn_type": np.zeros((S, N), np.int8),
            "dyn_bool": np.zeros((S, N), np.int8),
            # 0/1 = the value coerces to that bool ("true"/"false"
            # strings included, equal.go), 2 = no bool coercion
            "dyn_as_bool": np.full((S, N), 2, np.int8),
            "dyn_num": np.zeros((S, N), np.float32),
            "dyn_has_num": np.zeros((S, N), np.int8),
            # canonical number hash (rows carry canon hashes, not floats)
            "dyn_num_h": np.zeros((S, N, 2), np.uint32),
            "dyn_sprint": np.zeros((S, N, 2), np.uint32),
            "dyn_list_h": np.zeros((S, N, L, 2), np.uint32),
            "dyn_list_n": np.zeros((S, N), np.int32),
            # string value that decodes as a JSON string-array
            "dyn_json_list": np.zeros((S, N), np.int8),
            # host-completion flag: list overflow, glob/unit-bearing
            # values, or glob-bearing guarded resource values —
            # anything hash lanes can't compare the way the oracle does
            "dyn_host": np.zeros((S, N), np.int8),
        }
        cache: Dict[Any, Tuple[bool, Any]] = {}
        # scope backend-failure poisoning to this batch: a dead backend
        # costs ONE retry budget here, not one per (slot, resource)
        begin_batch = getattr(self.data_sources, "begin_batch", None)
        if begin_batch is not None:
            begin_batch()
        try:
            return self._encode_dyn_cells(resources, operations,
                                          admission_infos, lanes, cache)
        finally:
            end_batch = getattr(self.data_sources, "end_batch", None)
            if end_batch is not None:
                end_batch()

    def _encode_dyn_cells(self, resources, operations, admission_infos,
                          lanes, cache):
        import json as _json

        from ..engine.contextloaders import load_context_entries
        from ..utils.wildcard import contains_wildcard

        L = self.DYN_LIST_L
        for ci, res in enumerate(resources):
            op = (operations[ci] if operations else "") or ""
            info = admission_infos[ci] if admission_infos else None
            # ONE context build per resource (image extraction is the
            # expensive part); every slot loads into a shallow fork so
            # entries one slot resolves never leak into another slot's
            # substitution or query
            base_ctx = _scan_json_context(res, op, info)
            for si, slot in enumerate(self.cps.dyn_slots):
                ctx = base_ctx.shallow_fork()
                key = None
                try:
                    from ..engine.variables import substitute_all

                    key = (si, _json.dumps(
                        substitute_all(ctx, slot.entries), sort_keys=True,
                        default=str))
                except Exception:  # noqa: BLE001
                    key = None  # request-dependent substitution failed
                if key is not None and key in cache:
                    ok, val = cache[key]
                else:
                    try:
                        load_context_entries(ctx, slot.entries,
                                             self.data_sources)
                        val = ctx.query(slot.query)
                        ok = True
                    except Exception:  # noqa: BLE001
                        ok, val = False, None
                    if key is not None:
                        cache[key] = (ok, val)
                if not ok:
                    lanes["dyn_type"][si, ci] = 0
                    continue
                self._fill_dyn_value(lanes, si, ci, val, L)
                # guarded resource paths: glob-bearing string values
                # defeat hash membership -> host completes the cell
                for segs in slot.guard_paths:
                    for v in _walk_values(res, segs):
                        if isinstance(v, str) and contains_wildcard(v):
                            lanes["dyn_host"][si, ci] = 1
        return lanes

    @staticmethod
    def _fill_dyn_value(lanes, si, ci, val, L):
        from ..engine.pattern import go_parse_float
        from ..utils.duration import parse_duration
        from ..utils.quantity import parse_quantity
        from ..utils.wildcard import contains_wildcard
        from .flatten import go_sprint
        from .hashing import canon_number, hash_str, split32

        if isinstance(val, bool):
            lanes["dyn_type"][si, ci] = 2
            lanes["dyn_bool"][si, ci] = 1 if val else 0
            lanes["dyn_as_bool"][si, ci] = 1 if val else 0
        elif isinstance(val, (int, float)):
            lanes["dyn_type"][si, ci] = 3
            lanes["dyn_num"][si, ci] = float(val)
            lanes["dyn_has_num"][si, ci] = 1
            lanes["dyn_num_h"][si, ci] = split32(canon_number(val))
        elif isinstance(val, str):
            lanes["dyn_type"][si, ci] = 4
            lanes["dyn_sprint"][si, ci] = split32(hash_str(val, tag="s"))
            if val in ("true", "false"):
                lanes["dyn_as_bool"][si, ci] = 1 if val == "true" else 0
            f = go_parse_float(val)
            if f is not None:
                lanes["dyn_num"][si, ci] = f
                lanes["dyn_has_num"][si, ci] = 1
                lanes["dyn_num_h"][si, ci] = split32(canon_number(f))
            # globs act as patterns, unit strings coerce, and range
            # expressions compare structurally in the oracle — hash
            # equality can't see any of those
            from ..engine.operator import (Operator,
                                           get_operator_from_string_pattern)

            if contains_wildcard(val):
                lanes["dyn_host"][si, ci] = 1
            if (val != "0" and parse_duration(val) is not None) or \
                    (f is None and parse_quantity(val) is not None):
                lanes["dyn_host"][si, ci] = 1
            if get_operator_from_string_pattern(val) in (
                    Operator.IN_RANGE, Operator.NOT_IN_RANGE):
                lanes["dyn_host"][si, ci] = 1
            # a valid-JSON string-array value decodes for membership
            # (in.go keyExistsInArray / anyin.go _value_as_string_list)
            from ..engine.conditions import _value_as_string_list

            arr = _value_as_string_list(val)
            if arr is not None:
                lanes["dyn_json_list"][si, ci] = 1
                if len(arr) > L:
                    lanes["dyn_host"][si, ci] = 1
                n = 0
                for v in arr[:L]:
                    if contains_wildcard(v):
                        lanes["dyn_host"][si, ci] = 1
                    lanes["dyn_list_h"][si, ci, n] = split32(
                        hash_str(v, tag="s"))
                    n += 1
                lanes["dyn_list_n"][si, ci] = n
        elif val is None:
            lanes["dyn_type"][si, ci] = 1
        elif isinstance(val, list):
            lanes["dyn_type"][si, ci] = 5
            if len(val) > L:
                lanes["dyn_host"][si, ci] = 1
            n = 0
            for v in val[:L]:
                s = go_sprint(v)
                if s is None:
                    lanes["dyn_host"][si, ci] = 1
                    continue
                if contains_wildcard(s):
                    lanes["dyn_host"][si, ci] = 1
                lanes["dyn_list_h"][si, ci, n] = split32(hash_str(s, tag="s"))
                n += 1
            lanes["dyn_list_n"][si, ci] = n
        else:
            lanes["dyn_type"][si, ci] = 6

    # -- evaluation

    # batch sizes bucket to powers of two so arbitrary N never triggers
    # unbounded XLA recompiles (SURVEY §7 "recompilation churn": the
    # jit cache is keyed by shape; bucketing caps it at ~log2 shapes)
    MIN_BUCKET = 16

    def bucket_size(self, n: int) -> int:
        b = self.MIN_BUCKET
        while b < n:
            b *= 2
        return b

    # -- rule analytics (observability/analytics.py)

    def rule_idents(self) -> List[RuleIdent]:
        """Per-rule analytics identities aligned with cps.rules rows:
        (policy spec hash, names, on-device placement). Exception-named
        rules report as host — that is where their verdicts resolve."""
        if self._rule_idents is None:
            hashes = self.cps.policy_spec_hashes()
            self._rule_idents = [
                RuleIdent(policy_hash=hashes[e.policy_idx],
                          policy_name=e.policy_name,
                          rule_name=e.rule_name,
                          on_device=(e.device_row is not None
                                     and ri not in self._exception_rules))
                for ri, e in enumerate(self.cps.rules)]
        return self._rule_idents

    def set_pending_counts(self, counts: Optional[np.ndarray]) -> None:
        """Stash the device-side per-rule verdict-class reduction for
        the assemble() that follows on this thread. With a corrupt-mode
        fault armed at the dispatch site the post-readback table may be
        altered behind the counts — drop them so analytics fall back to
        counting the (corrupted) truth the verdict path actually
        serves."""
        if counts is not None:
            spec = global_faults.armed().get(SITE_TPU_DISPATCH)
            if spec is not None and spec.mode == "corrupt":
                counts = None
        self._tls.pending_counts = counts

    def confirm_seen(self) -> bool:
        """Did the last scan() on this thread resolve any pattern-
        CONFIRM cell? Flight-recorder outcome classification: a batch
        that exercised the approximate-DFA confirmation ladder is
        always captured (ISSUE: CONFIRM is an always-capture outcome).
        Batch-scoped — scan() clears it at entry, assemble() sets it
        from the device table."""
        return bool(getattr(self._tls, "confirm_seen", False))

    def take_pending_counts(self) -> Optional[np.ndarray]:
        counts = getattr(self._tls, "pending_counts", None)
        self._tls.pending_counts = None
        if counts is not None and (
                not isinstance(counts, np.ndarray)
                or counts.shape != (len(self.cps.device_programs),
                                    NUM_CLASSES)):
            return None
        return counts

    # -- verdict-column caching (tpu/cache.py)

    @property
    def cache_eligible(self) -> bool:
        """A compiled set may serve verdicts from the content-addressed
        cache only when evaluation is a pure function of the cache key:
        no runtime dyn-operand slots (they do real context-backend I/O
        per request), and no statically host-routed rule with context
        entries (the scalar oracle would load them live). Compile-time
        folded configmaps are fine — their content hashes are part of
        the policy-set key, so movement rotates the key."""
        if self._cache_eligible is None:
            eligible = not self.cps.dyn_slots
            if eligible:
                for ri, entry in enumerate(self.cps.rules):
                    if (entry.device_row is not None
                            and ri not in self._exception_rules):
                        continue
                    policy = self.cps.policies[entry.policy_idx]
                    for rule in policy.get_rules():
                        if rule.name == entry.rule_name and rule.context:
                            eligible = False
            self._cache_eligible = eligible
        return self._cache_eligible

    def verdict_cache_keys(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
        resource_hashes: Optional[Sequence[Optional[str]]] = None,
    ) -> Optional[List[Optional[Tuple[str, str, str]]]]:
        """Per-resource verdict-cache keys, or None when this engine is
        not cache eligible. Individual entries are None for resources
        that cannot be content-hashed (those bypass the cache).
        ``resource_hashes`` lets callers that already hold the content
        hash (the cluster snapshot stores one per resource) skip the
        re-serialization — it MUST be the canonical sha-16 the snapshot
        computes, which is the same function used here."""
        from .cache import request_digest, resource_content_hash

        if not self.cache_eligible:
            return None
        if self._cache_ident is None:
            self._cache_ident = self.cps.cache_key() + self._exceptions_digest
        ns_labels = namespace_labels or {}
        keys: List[Optional[Tuple[str, str, str]]] = []
        for ci, res in enumerate(resources):
            h = (resource_hashes[ci] if resource_hashes is not None
                 else resource_content_hash(res))
            if h is None:
                keys.append(None)
                continue
            try:
                meta = res.get("metadata") or {}
                nsl = ns_labels.get(
                    meta.get("name", "") if res.get("kind") == "Namespace"
                    else meta.get("namespace", ""), {})
            except Exception:  # not dict-shaped
                keys.append(None)
                continue
            op = (operations[ci] if operations else "") or ""
            info = admission_infos[ci] if admission_infos else None
            keys.append((self._cache_ident, h,
                         request_digest(nsl, op, info)))
        return keys

    def scan(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
        live_n: Optional[int] = None,
    ) -> ScanResult:
        """Cached scan: verdict columns for content-identical
        (resource, request) pairs restore from the LRU; only the misses
        pay encode + dispatch (via the full uncached ladder). Columns
        are per-resource independent in the device program, so a
        miss-only sub-batch is bit-identical to scanning everything.

        ``live_n`` marks the first N resources as real for the rule
        analytics (the serving pipeline pads its batches with empty
        resources — those must not inflate not-matched counts);
        verdicts are computed and returned for every column either
        way."""
        from .cache import global_verdict_cache as vc

        self._tls.confirm_seen = False  # batch-scoped (see confirm_seen)
        keys = (self.verdict_cache_keys(resources, namespace_labels,
                                        operations, admission_infos)
                if vc.enabled else None)
        if keys is None:
            if vc.enabled:
                vc.bypass()
            return self._scan_uncached(resources, namespace_labels,
                                       operations, admission_infos,
                                       live_n=live_n)
        n = len(resources)
        rules = [(e.policy_name, e.rule_name) for e in self.cps.rules]
        total = np.full((len(rules), n), NOT_MATCHED, dtype=np.int32)
        miss: List[int] = []
        hits: List[int] = []
        for i, key in enumerate(keys):
            col = (vc.get(key, expect_rows=len(rules))
                   if key is not None else None)
            if col is None:
                miss.append(i)
            else:
                hits.append(i)
                total[:, i] = col
        if miss:
            # miss indices ascend, and pad resources are a suffix of the
            # batch — so the sub-batch's live prefix is just a count
            sub_live = (sum(1 for i in miss if i < live_n)
                        if live_n is not None else None)
            sub = self._scan_uncached(
                [resources[i] for i in miss], namespace_labels,
                [operations[i] for i in miss] if operations else None,
                [admission_infos[i] for i in miss] if admission_infos
                else None, live_n=sub_live)
            for j, i in enumerate(miss):
                total[:, i] = sub.verdicts[:, j]
                if keys[i] is not None:
                    vc.put(keys[i], sub.verdicts[:, j])
        if hits and global_rule_stats.enabled:
            # cache-served verdicts still count: replay the hit columns
            # into the accumulator so a warm rescan reports the same
            # rule stats as a cold one
            live_hits = ([i for i in hits if i < live_n]
                         if live_n is not None else hits)
            if live_hits:
                global_rule_stats.ingest_table(
                    self.rule_idents(), total[:, live_hits],
                    source="cached")
                self.record_pattern_replay(len(live_hits))
        return ScanResult(verdicts=total, rules=rules)

    def record_pattern_replay(self, n_cols: int) -> None:
        """Pattern-cell accounting for cache-served verdict columns —
        the replay convention every cached path follows for rule stats
        applies to the pattern split too, so warm rescans report the
        same pattern work as the cold scan that populated the cache.
        Cached columns count as path=device (the stored verdict was
        device-derived; any confirmation happened at populate time)."""
        if not n_cols:
            return
        for ri, entry in enumerate(self.cps.rules):
            if entry.device_row is None or ri in self._exception_rules:
                if entry.pattern_host:
                    global_pattern_cells.record(entry.policy_name,
                                                host=n_cols)
                continue
            if getattr(self.cps.device_programs[entry.device_row],
                       "uses_patterns", False):
                global_pattern_cells.record(entry.policy_name,
                                            device=n_cols)

    def _scan_uncached(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
        live_n: Optional[int] = None,
    ) -> ScanResult:
        n = len(resources)
        padded_n = self.bucket_size(max(n, 1))
        padded = list(resources) + [{} for _ in range(padded_n - n)]
        ops = (list(operations) + [""] * (padded_n - n)) if operations else None
        infos = (list(admission_infos) + [None] * (padded_n - n)) \
            if admission_infos else None
        t_enc0 = time.perf_counter()
        try:
            with global_profiler.phase(PHASE_ENCODE), \
                    global_tracer.span("tpu.encode", resources=n,
                                       padded=padded_n):
                batch, rows, meta = self.encode(padded, namespace_labels,
                                                ops, infos)
        except Exception:
            # a hostile resource broke batch encoding: quarantine it so
            # the rest of the batch still evaluates (device or scalar),
            # and the bad resource degrades to scalar / per-rule ERROR
            return self._scan_quarantining(
                resources, namespace_labels, operations, admission_infos,
                live_n=live_n)
        t_enc = time.perf_counter() - t_enc0
        t_disp0 = time.perf_counter()
        device_table = self._dispatch(batch, padded_n, n)[:, :n]  # (D, N)
        # feed accounting: while the host encoded, the device sat idle
        # (the serial ladder has no overlap); dispatch + readback is
        # device-busy time. Only when the device actually ran: with the
        # breaker open / dispatch failed there is no device to starve,
        # and counting encode time would pin the gauge at 1.0 during an
        # outage — pointing operators at the encoder instead of the
        # device
        from ..observability.profiling import last_dispatch_path

        if last_dispatch_path() == PATH_DEVICE:
            global_starvation.record(busy_s=time.perf_counter() - t_disp0,
                                     starved_s=t_enc)
        return self.assemble(
            device_table, resources, namespace_labels, operations,
            admission_infos, live_n=live_n
        )

    def _breaker_open_fallback(self) -> None:
        from ..observability.metrics import global_registry

        self._tls.pending_counts = None  # no device truth this batch
        set_dispatch_path(PATH_SCALAR_FALLBACK)
        global_registry.breaker_fallback.inc({"reason": "open"})
        global_tracer.add_event("breaker_fallback", reason="open",
                                breaker=self.breaker.name)

    def _record_dispatch_failure(self, e: Exception) -> None:
        from ..observability.metrics import global_registry

        # a stash from a dispatch that then failed validation must not
        # masquerade as truth for the all-HOST fallback table
        self._tls.pending_counts = None
        self.breaker.record_failure()
        set_dispatch_path(PATH_SCALAR_FALLBACK)
        global_registry.breaker_fallback.inc({"reason": "error"})
        global_tracer.add_event(
            "breaker_fallback", reason="error", breaker=self.breaker.name,
            breaker_state=self.breaker.state,
            error=f"{type(e).__name__}: {e}")

    def guarded_dispatch(self, dispatch_fn, want_shape) -> Optional[np.ndarray]:
        """The ONE breaker-gated dispatch ladder (shared with
        ShardedScanner so the two paths cannot drift): fault hook,
        dispatch, corrupt filter, shape/dtype validation, breaker
        bookkeeping. Returns the validated verdict table, or None when
        the breaker is open or the dispatch failed — the caller falls
        back to scalar completion (all-HOST). The pipelined scan uses
        the same ladder split in two (guarded_launch/guarded_complete)
        so the device can run chunk k while the host touches k±1."""
        self._tls.pending_counts = None
        if not self.breaker.allow():
            self._breaker_open_fallback()
            return None
        try:
            with global_tracer.span("tpu.dispatch",
                                    breaker=self.breaker.state) as span:
                global_faults.fire(SITE_TPU_DISPATCH)
                if _sanitizer.ENABLED:
                    # lock-order sanitizer: any lock held across the
                    # device call serializes its waiters behind XLA
                    _sanitizer.note_device_dispatch()
                table = dispatch_fn()
                table = self._validate_device_table(table, want_shape)
                span.attributes["engine"] = PATH_DEVICE
                return table
        except Exception as e:
            self._record_dispatch_failure(e)
            return None

    def _validate_device_table(self, table, want_shape) -> np.ndarray:
        table = global_faults.corrupt(SITE_TPU_DISPATCH, table)
        if not (isinstance(table, np.ndarray)
                and table.shape == want_shape
                and np.issubdtype(table.dtype, np.integer)):
            raise DeviceResultError(
                f"device returned shape "
                f"{getattr(table, 'shape', None)}, want {want_shape}")
        self.breaker.record_success()
        set_dispatch_path(PATH_DEVICE)
        return table

    def guarded_launch(self, launch_fn) -> Optional[Tuple[Any]]:
        """Phase 1 of the async dispatch ladder (tpu/pipeline.py):
        breaker gate + fault hook + async launch (device_put + jitted
        call, NO blocking readback). Returns an opaque in-flight handle
        for guarded_complete, or None when the breaker is open or the
        launch itself raised — same fallback semantics as
        guarded_dispatch."""
        self._tls.pending_counts = None
        if not self.breaker.allow():
            self._breaker_open_fallback()
            return None
        try:
            global_faults.fire(SITE_TPU_DISPATCH)
            if _sanitizer.ENABLED:
                _sanitizer.note_device_dispatch()
            return (launch_fn(),)
        except Exception as e:
            self._record_dispatch_failure(e)
            return None

    def guarded_complete(self, handle: Optional[Tuple[Any]], readback_fn,
                         want_shape) -> Optional[np.ndarray]:
        """Phase 2: blocking readback + corrupt filter + shape/dtype
        validation + breaker bookkeeping. A None handle (failed launch)
        passes through as None — the caller scalar-completes, exactly
        like a failed guarded_dispatch."""
        if handle is None:
            return None
        try:
            return self._validate_device_table(readback_fn(handle[0]),
                                               want_shape)
        except Exception as e:
            self._record_dispatch_failure(e)
            return None

    def _dispatch(self, batch, padded_n: int,
                  n_live: Optional[int] = None) -> np.ndarray:
        """One device dispatch through the guarded ladder. Any failure
        returns an all-HOST table, which routes the WHOLE batch through
        the scalar oracle in assemble(): verdicts stay bit-identical,
        only latency degrades. The device program also returns the
        per-rule verdict-class reduction; it is stashed (pad columns
        subtracted) for the assemble() that follows this dispatch."""
        if n_live is None:
            n_live = padded_n

        def run():
            import jax

            # one batched H2D put for the whole lane dict — per-lane
            # transfer pays a link round-trip per array (see batch_to_host).
            # dispatch (async launch + any XLA compile at this shape) and
            # readback (the blocking D2H) are attributed separately
            with maybe_xla_trace():
                with global_profiler.phase(PHASE_DISPATCH):
                    out = self.cps.device_fn()(jax.device_put(batch))
                with global_profiler.phase(PHASE_READBACK):
                    # tolerate monkeypatched device_fns that still
                    # return a bare verdict table
                    if isinstance(out, tuple):
                        table, counts = np.asarray(out[0]), np.asarray(out[1])
                    else:
                        table, counts = np.asarray(out), None
            if counts is not None and table.ndim == 2:
                # bucket-pad columns are encoded empties, not workload:
                # their contribution leaves the analytics counts here
                counts = counts.astype(np.int64) - class_counts(
                    table[:, n_live:])
            self.set_pending_counts(counts)
            return table

        D = len(self.cps.device_programs)
        table = self.guarded_dispatch(run, (D, padded_n))
        if table is None:
            return np.full((D, padded_n), HOST, dtype=np.int32)
        return table

    def _scan_quarantining(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
        live_n: Optional[int] = None,
    ) -> ScanResult:
        """Batch encode failed: split the batch into resources that
        encode alone (re-scanned as a clean sub-batch) and hostile ones,
        which complete per (policy, resource) on the scalar engine — a
        policy the scalar engine ALSO cannot evaluate yields per-rule
        ERROR verdicts instead of aborting the scan."""
        n = len(resources)
        good: List[int] = []
        bad: List[int] = []
        for ci, res in enumerate(resources):
            op = [(operations[ci] if operations else "") or ""]
            info = [admission_infos[ci]] if admission_infos else None
            try:
                # STRUCTURAL probe only (rows + meta lanes): dyn-lane
                # encoding does real context-backend I/O and catches its
                # own load errors, so probing it here would pay O(batch)
                # duplicate backend calls for nothing. A dyn-lane value
                # that still throws re-enters quarantine from the good
                # sub-batch's scan, which then degrades it to scalar.
                encode_resources([res], self.cps.encode_cfg,
                                 self.cps.byte_paths, self.cps.key_byte_paths)
                encode_metadata([res], namespace_labels, op, info,
                                self.cps.meta_cfg)
                good.append(ci)
            except Exception:
                bad.append(ci)
        if not bad:
            # batch-level failure with no single culprit: degrade the
            # whole batch to the scalar path rather than loop forever
            good, bad = [], list(range(n))
        total = np.full((len(self.cps.rules), n), NOT_MATCHED, dtype=np.int32)
        if good:
            sub = self.scan(
                [resources[i] for i in good], namespace_labels,
                [operations[i] for i in good] if operations else None,
                [admission_infos[i] for i in good] if admission_infos else None,
                live_n=(sum(1 for i in good if i < live_n)
                        if live_n is not None else None))
            total[:, good] = sub.verdicts
        ns_labels = namespace_labels or {}
        for ci in bad:
            res = resources[ci]
            op = (operations[ci] if operations else "") or ""
            info = admission_infos[ci] if admission_infos else None
            try:
                kind = res.get("kind", "")
                meta = res.get("metadata") or {}
                nsl = ns_labels.get(
                    meta.get("name", "") if kind == "Namespace"
                    else meta.get("namespace", ""), {})
            except Exception:  # not even dict-shaped
                nsl = {}
            for pi, policy in enumerate(self.cps.policies):
                try:
                    pctx = build_scan_context(policy, res, nsl, op, info)
                    verdicts = _scalar_rule_verdicts(self.scalar, policy, pctx)
                except Exception:
                    verdicts = None  # ERROR every rule of this policy
                for ri, entry in enumerate(self.cps.rules):
                    if entry.policy_idx != pi:
                        continue
                    total[ri, ci] = ERROR if verdicts is None \
                        else verdicts.get(entry.rule_name, NOT_MATCHED)
        # analytics: the good sub-batch ingested inside self.scan();
        # only the quarantined columns are counted here
        live_bad = [ci for ci in bad if live_n is None or ci < live_n]
        if live_bad and global_rule_stats.enabled:
            global_rule_stats.ingest_table(self.rule_idents(),
                                           total[:, live_bad],
                                           source="quarantine")
        return ScanResult(
            verdicts=total,
            rules=[(e.policy_name, e.rule_name) for e in self.cps.rules],
        )

    def assemble(
        self,
        device_table: np.ndarray,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
        live_n: Optional[int] = None,
    ) -> ScanResult:
        """Merge device verdicts with host completions (host rules +
        HOST-flagged resources), then fold the batch into the rule
        analytics: device-reduced counts for untouched device rows,
        per-cell corrections for everything the host completed."""
        n = len(resources)
        total = np.full((len(self.cps.rules), n), NOT_MATCHED, dtype=np.int32)
        ns_labels = namespace_labels or {}
        # unconditional assignment, not a sticky set: the pipelined
        # scan calls assemble() per chunk WITHOUT going through scan()
        # (the only other place the flag resets), so one CONFIRM cell
        # must not mark every later chunk/tick as a confirm outcome
        self._tls.confirm_seen = bool(
            (np.asarray(device_table) == CONFIRM).any())

        # requests whose identity strings carry globs defeat the
        # device's hash-equality userInfo lanes (_set_in matches
        # wildcards in either direction) -> per-cell host completion
        glob_identity_cis: List[int] = []
        if admission_infos:
            from ..utils.wildcard import contains_wildcard

            for ci in range(n):
                info = admission_infos[ci] if ci < len(admission_infos) else None
                if info is not None and any(
                        contains_wildcard(g) for g in (info.groups or [])):
                    glob_identity_cis.append(ci)

        # which (policy, resource) pairs need the scalar engine? HOST
        # and CONFIRM cells both resolve there — CONFIRM is the
        # pattern-confirmation sub-batch (over-approximate DFA hits,
        # byte-sensitive patterns on non-ASCII subjects), attributed
        # separately in the pattern-cell accounting below
        host_cells: Dict[Tuple[int, int], None] = {}
        live = n if live_n is None else min(live_n, n)
        for ri, entry in enumerate(self.cps.rules):
            if entry.device_row is None or ri in self._exception_rules:
                for ci in range(n):
                    host_cells[(entry.policy_idx, ci)] = None
                if entry.pattern_host and live:
                    # non-lowerable pattern kept this rule on the host
                    global_pattern_cells.record(entry.policy_name,
                                                host=live)
            else:
                row = device_table[entry.device_row].copy()
                if glob_identity_cis and self.cps.device_programs[
                        entry.device_row].uses_userinfo:
                    row[glob_identity_cis] = HOST
                total[ri] = row
                for ci in np.nonzero(row >= HOST)[0]:
                    host_cells[(entry.policy_idx, int(ci))] = None
                if live and getattr(
                        self.cps.device_programs[entry.device_row],
                        "uses_patterns", False):
                    # path attribution for a LOWERED pattern rule:
                    # device = the DFA verdict stood, confirm = the
                    # oracle confirmed a maybe. Its HOST cells are NOT
                    # pattern-caused (encode caps, userinfo globs, CEL
                    # DELETE diversion) and stay out of the split —
                    # path="host" means exactly the non-lowerable
                    # pattern rules counted in the branch above.
                    rowv = row[:live]
                    c = int((rowv == CONFIRM).sum())
                    h = int((rowv == HOST).sum())
                    global_pattern_cells.record(entry.policy_name,
                                                device=live - c - h,
                                                confirm=c)
                    if c:
                        # the ongoing price of over-approximated /
                        # byte-sensitive tables: cells the oracle had
                        # to re-check (kyverno_dfa_confirm_cells_total)
                        try:
                            from ..observability.metrics import (
                                global_registry as _reg)
                            _reg.dfa_confirm_cells.inc(value=c)
                        except Exception:  # noqa: BLE001
                            pass

        from ..engine.match import matches_resource_description

        cache: Dict[Tuple[int, int], Optional[Dict[str, int]]] = {}
        with global_profiler.phase(PHASE_HOST_COMPLETE):
            for (pi, ci) in host_cells:
                policy = self.cps.policies[pi]
                res = resources[ci]
                try:
                    kind = res.get("kind", "")
                    ns = (res.get("metadata") or {}).get("namespace", "")
                    nsl = ns_labels.get((res.get("metadata") or {}).get("name", "") if kind == "Namespace" else ns, {})
                    op = (operations[ci] if operations else "") or ""
                    info = admission_infos[ci] if admission_infos else None
                    # pre-screen with the (cheap) matcher before paying for
                    # context construction + full validation: in a realistic
                    # mix most host (policy, resource) cells are simply not
                    # matched (kind/selector mismatch), making the fallback
                    # cost scale with MATCHED cells, not policies x resources
                    if not any(
                            not matches_resource_description(
                                res, rule, info, nsl,
                                policy_namespace=policy.namespace,
                                operation=op or "CREATE")
                            for rule in policy.get_rules() if rule.has_validate()):
                        cache[(pi, ci)] = {}  # every rule NOT_MATCHED
                        continue
                    pctx = build_scan_context(policy, res, nsl, op, info)
                    cache[(pi, ci)] = _scalar_rule_verdicts(self.scalar, policy, pctx)
                except Exception:
                    # the scalar oracle itself choked on this (policy,
                    # resource) — a quarantined policy whose pattern is
                    # genuinely broken lands here. The cell reports
                    # per-rule ERROR; the rest of the batch is untouched.
                    cache[(pi, ci)] = None
        # merge indexed by policy: each rule row only visits its own
        # policy's completed cells, so the pass is O(rules + host_cells)
        # instead of quadratic on large policy sets
        by_policy: Dict[int, List[Tuple[int, Optional[Dict[str, int]]]]] = {}
        for (pi, ci), verdicts in cache.items():
            by_policy.setdefault(pi, []).append((ci, verdicts))
        # cells whose device verdict the host replaced, per device rule
        # — the analytics correction set (device counts already include
        # the device's original code for these cells)
        replaced: Dict[int, List[int]] = {}
        for ri, entry in enumerate(self.cps.rules):
            cells = by_policy.get(entry.policy_idx)
            if not cells:
                continue
            host_rule = (entry.device_row is None
                         or ri in self._exception_rules)
            for ci, verdicts in cells:
                if host_rule or total[ri, ci] >= HOST:
                    # pre-screened cells carry no verdict rows: the
                    # whole policy was unmatched (HOST must not escape)
                    total[ri, ci] = ERROR if verdicts is None \
                        else verdicts.get(entry.rule_name, NOT_MATCHED)
                    if not host_rule:
                        replaced.setdefault(ri, []).append(ci)

        self._ingest_assembled(total, device_table, replaced, live_n)
        return ScanResult(
            verdicts=total,
            rules=[(e.policy_name, e.rule_name) for e in self.cps.rules],
        )

    def _ingest_assembled(self, total: np.ndarray, device_table: np.ndarray,
                          replaced: Dict[int, List[int]],
                          live_n: Optional[int]) -> None:
        """Exact per-rule verdict counts for one assembled batch.

        With the device-side reduction stashed by the dispatch, a
        device rule's counts are the O(1)-per-rule device totals plus a
        correction per host-completed cell (subtract the device's code,
        add the final one) — the correction set is exactly the cell set
        the host already paid scalar work for. Without a stash (breaker
        fallback, scalar completion, external tables) the counts come
        from one vectorized host reduction over the final table; either
        way the ingested numbers describe the verdicts actually
        served."""
        if not global_rule_stats.enabled or total.shape[0] == 0:
            return
        rules_n, n = total.shape
        dev_counts = self.take_pending_counts()
        if dev_counts is None:
            counts = class_counts(total)
            source = "host"
        else:
            counts = np.zeros((rules_n, NUM_CLASSES), dtype=np.int64)
            host_rows: List[int] = []
            for ri, entry in enumerate(self.cps.rules):
                if entry.device_row is None or ri in self._exception_rules:
                    host_rows.append(ri)
                    continue
                c = dev_counts[entry.device_row].astype(np.int64).copy()
                for ci in replaced.get(ri, ()):
                    c[int(device_table[entry.device_row, ci])] -= 1
                    c[int(total[ri, ci])] += 1
                counts[ri] = c
            if host_rows:
                counts[host_rows] = class_counts(total[host_rows])
            source = "device"
        if live_n is not None and live_n < n:
            counts = counts - class_counts(total[:, live_n:])
        global_rule_stats.ingest_counts(self.rule_idents(), counts,
                                        source=source)

    # -- mutate triage (mutation/): which resources need the patcher?

    @property
    def mutate_cache_eligible(self) -> bool:
        """Mutate-side purity: no host-routed or excepted mutate rule
        carries context entries (the scalar patcher would load them
        live per request, so a replay — cached triage rows feeding a
        shadow-verification re-patch — could observe different state).
        Device-compiled triage rules are pure by construction: dyn-slot
        programs are refused at compile and folded context hashes are
        part of the policy-set key."""
        if self._mutate_cache_eligible is None:
            eligible = True
            for mi, entry in enumerate(self.cps.mutate_entries):
                if (entry.device_row is not None
                        and mi not in self._exception_mutate_rules):
                    continue
                policy = self.cps.policies[entry.policy_idx]
                for rule in policy.get_rules():
                    if rule.name == entry.rule_name and rule.context:
                        eligible = False
            self._mutate_cache_eligible = eligible
        return self._mutate_cache_eligible

    def mutate_triage_cache_keys(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
    ) -> Optional[List[Optional[Tuple[str, str, str]]]]:
        """Verdict-cache keys for triage rows: the validate keys with a
        namespaced ident, so an (M,) triage column and an (R,) validate
        column for the same (resource, request) can never collide."""
        if not self.mutate_cache_eligible:
            return None
        keys = self.verdict_cache_keys(resources, namespace_labels,
                                       operations, admission_infos)
        if keys is None:
            return None
        return [None if k is None else ("mutate|" + k[0], k[1], k[2])
                for k in keys]

    def triage_mutate(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
    ) -> MutateTriageResult:
        """Needs-mutation triage over the compiled mutate bank — the
        same cached ladder as scan(): verdict-cache columns for warm
        (resource, request) pairs, guarded dispatch for the misses,
        all-HOST degradation (everything scalar-patches) on any
        failure."""
        from ..observability.metrics import global_registry as reg
        from .cache import global_verdict_cache as vc

        rules = self.cps.mutate_rules
        m, n = len(rules), len(resources)
        if m == 0 or n == 0:
            return MutateTriageResult(
                np.zeros((m, n), dtype=np.int32), rules)
        keys = (self.mutate_triage_cache_keys(
                    resources, namespace_labels, operations,
                    admission_infos)
                if vc.enabled else None)
        if keys is None:
            return self._triage_uncached(resources, namespace_labels,
                                         operations, admission_infos)
        total = np.full((m, n), HOST, dtype=np.int32)
        miss: List[int] = []
        hits = 0
        for i, key in enumerate(keys):
            col = (vc.get(key, expect_rows=m)
                   if key is not None else None)
            if col is None:
                miss.append(i)
            else:
                hits += 1
                total[:, i] = col
        if hits:
            reg.mutate_triage.inc({"outcome": "cached"}, hits)
        if miss:
            sub = self._triage_uncached(
                [resources[i] for i in miss], namespace_labels,
                [operations[i] for i in miss] if operations else None,
                [admission_infos[i] for i in miss] if admission_infos
                else None)
            for j, i in enumerate(miss):
                total[:, i] = sub.verdicts[:, j]
                if keys[i] is not None:
                    vc.put(keys[i], sub.verdicts[:, j])
        return MutateTriageResult(verdicts=total, rules=rules)

    def _triage_uncached(
        self,
        resources: Sequence[Dict[str, Any]],
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        operations: Optional[Sequence[str]] = None,
        admission_infos: Optional[Sequence[Optional[RequestInfo]]] = None,
    ) -> MutateTriageResult:
        """One device batch over the mutate bank. Host rows (bank host
        entries, excepted rules, userinfo globs) stay HOST; encode or
        dispatch failure degrades the whole batch to HOST — the
        coordinator then scalar-patches everything, bit-identically."""
        from ..observability.metrics import global_registry as reg

        rules = self.cps.mutate_rules
        m, n = len(rules), len(resources)
        total = np.full((m, n), HOST, dtype=np.int32)
        d = len(self.cps.mutate_programs)
        device_table = None
        if d:
            padded_n = self.bucket_size(max(n, 1))
            padded = list(resources) + [{} for _ in range(padded_n - n)]
            ops = (list(operations) + [""] * (padded_n - n)) \
                if operations else None
            infos = (list(admission_infos) + [None] * (padded_n - n)) \
                if admission_infos else None
            try:
                with global_profiler.phase(PHASE_ENCODE), \
                        global_tracer.span("tpu.encode_triage",
                                           resources=n, padded=padded_n):
                    batch, _, _ = self.encode(padded, namespace_labels,
                                              ops, infos)
            except Exception:  # hostile resource: everything scalar
                batch = None
            if batch is not None:
                def run():
                    import jax

                    global_faults.fire(SITE_MUTATE_TRIAGE)
                    with maybe_xla_trace():
                        with global_profiler.phase(PHASE_DISPATCH):
                            out = self.cps.mutate_device_fn()(
                                jax.device_put(batch))
                        with global_profiler.phase(PHASE_READBACK):
                            return np.asarray(out)

                device_table = self.guarded_dispatch(run, (d, padded_n))
        if device_table is not None:
            glob_cis: List[int] = []
            if admission_infos:
                from ..utils.wildcard import contains_wildcard

                for ci in range(n):
                    info = (admission_infos[ci]
                            if ci < len(admission_infos) else None)
                    if info is not None and any(
                            contains_wildcard(g)
                            for g in (info.groups or [])):
                        glob_cis.append(ci)
            for mi, entry in enumerate(self.cps.mutate_entries):
                if (entry.device_row is None
                        or mi in self._exception_mutate_rules):
                    continue  # stays HOST
                row = device_table[entry.device_row, :n].copy()
                if glob_cis and self.cps.mutate_programs[
                        entry.device_row].uses_userinfo:
                    row[glob_cis] = HOST
                total[mi] = row
            reg.mutate_triage.inc({"outcome": "device"})
        else:
            reg.mutate_triage.inc({"outcome": "fallback"})
        result = MutateTriageResult(verdicts=total, rules=rules)
        for label, count in result.counts().items():
            if count:
                reg.mutate_triage_rows.inc({"result": label}, count)
        return result

    # -- introspection

    def coverage(self) -> Tuple[int, int]:
        return self.cps.coverage()

    def mutate_coverage(self) -> Tuple[int, int]:
        return self.cps.mutate_coverage()
